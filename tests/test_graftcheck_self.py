"""The self-scan gate: the repo is clean under its own linter (modulo
justified inline waivers), every registered jaxpr contract holds on the
CPU backend — including the recompile sentinel and the
callback/pallas-detection machinery itself — and the Layer-3 cost pass
(COSTS.json lockfile + quantitative cost contracts) is green on the tree
while each planted-regression fixture fails it with the drifting
primitives named.
"""

import os
import sys

import jax
import jax.numpy as jnp
import pytest

from cpgisland_tpu.analysis import (
    contracts,
    cost_contracts,
    costmodel,
    mem_contracts,
    memmodel,
    run_lint,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "cpgisland_tpu")
COST_FIXTURES = os.path.join(
    os.path.dirname(__file__), "fixtures", "graftcheck"
)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_self_scan_clean():
    result = run_lint([PKG], base=REPO)
    assert result.files_checked > 40
    bad = [f.format() for f in result.unwaived]
    assert bad == [], "\n".join(bad)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_self_scan_waivers_all_used_and_justified():
    result = run_lint([PKG], base=REPO)
    # Every waiver in the tree covers a live finding (no stale exemptions)
    # and carries a reason (parse_waivers enforces the reason; double-check
    # through the applied findings).
    assert result.unused_waivers == [], result.unused_waivers
    assert result.waived, "expected the documented intentional exemptions"
    for f in result.waived:
        assert f.waiver_reason


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_contracts_all_hold_on_cpu():
    results = contracts.run_contracts(execute=True)
    assert len(results) >= 10
    bad = {r.name: r.violations for r in results if not r.ok}
    assert bad == {}, bad
    byname = {r.name: r for r in results}
    # The reduced engines must have traced to their XLA twins off-TPU.
    assert byname["decode.onehot"].notes["pallas_calls"] == 0
    assert byname["em.seq.onehot"].notes["pallas_calls"] == 0
    # The dense pallas decode engine legitimately traces pallas_call (it
    # runs interpreted off-TPU in tests) — the detector must SEE them.
    assert byname["decode.pallas"].notes["pallas_calls"] > 0
    assert byname["engines.routing"].notes["auto_picks"]["decode"] == "xla"


def test_contract_summary_shape():
    results = contracts.run_contracts(execute=False)
    summary = contracts.summarize(results)
    assert summary["ok"] is True
    assert summary["checked"] == len(results)
    assert summary["violations"] == {}


def test_contract_detects_callback_primitive():
    def noisy(x):
        jax.debug.print("x={x}", x=x)
        return x * 2

    c = contracts.Contract(
        name="fixture.callback",
        make=lambda: (noisy, (jnp.ones(8),), None),
    )
    res = contracts.check_contract(c, execute=False)
    assert not res.ok
    assert any("callback" in v for v in res.violations)


def test_contract_detects_unstable_dispatch():
    # A jitted fn whose input SHAPE changes between the two stability
    # executions recompiles; the sentinel must catch it.
    fn = jax.jit(lambda x: x * 2)
    c = contracts.Contract(
        name="fixture.unstable",
        make=lambda: (fn, (jnp.ones(8),), (jnp.ones(16),)),
        stability=True,
    )
    res = contracts.check_contract(c, execute=True)
    assert not res.ok
    assert any("dispatch surface unstable" in v for v in res.violations)


def test_contract_pallas_expectation_is_platform_aware():
    if jax.default_backend() == "tpu":
        pytest.skip("off-TPU expectation test")
    # An entry that traces pallas off-TPU without the allowance violates.
    from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel

    params = contracts._flagship()
    o1, _ = contracts._obs_pair(2048, "int32")
    c = contracts.Contract(
        name="fixture.pallas-off-tpu",
        make=lambda: (
            lambda o: viterbi_parallel(
                params, o, block_size=256, engine="pallas"
            ),
            (o1,), None,
        ),
    )
    res = contracts.check_contract(c, execute=False)
    assert not res.ok
    assert any("XLA twin" in v for v in res.violations)


# -- Layer 3: the cost pass on the tree --------------------------------------


@pytest.fixture(scope="module")
def cost_report():
    return cost_contracts.run_cost_pass()


def test_cost_pass_green_on_tree(cost_report):
    assert cost_report["ok"], {
        "diff": cost_report["diff"]["violations"],
        "contracts": [
            (r["name"], r["violations"])
            for r in cost_report["contracts"] if not r["ok"]
        ],
    }
    # The committed lockfile covers the whole registry — no stale entries,
    # nothing unbaselined.
    assert cost_report["diff"]["stale"] == []
    assert cost_report["diff"]["checked"] >= 11


def test_cost_contracts_all_present(cost_report):
    names = {r["name"] for r in cost_report["contracts"]}
    assert names == {
        "cost.reduced-no-dense-pair", "cost.em-body-fixed-share",
        "cost.pass-structure", "cost.serial-depth-lanes",
    }


def test_pass_structure_matches_documented(cost_report):
    byname = {r["name"]: r for r in cost_report["contracts"]}
    notes = byname["cost.pass-structure"]["notes"]
    # BASELINE.md's documented pass structure after the r9 pass-count
    # collapse: decode keeps 3 (its passes are data-dependent), the
    # reduced probability-space paths run the co-scheduled fwd/bwd pass —
    # posterior/em-seq 2, chunked EM 1; the dense chunked twin keeps its
    # split 2 (cs-scaled stats need the split backward).
    assert notes["decode.onehot"] == 3
    assert notes["decode.batch_flat.scores.onehot"] == 3
    assert notes["posterior.onehot"] == 2
    assert notes["em.seq.onehot"] == 2
    assert notes["em.chunked.onehot"] == 1
    assert notes["em.chunked.xla"] == 2
    # ISSUE 17: the matrix-carried one-pass arm folds the products pass
    # into the co-scheduled launch — ONE T-scaling pass on both reduced
    # paths (the 2-pass entries above stay pinned as the shipped default
    # and A/B baseline).
    assert notes["posterior.onehot.onepass"] == 1
    assert notes["em.seq.onehot.onepass"] == 1


# -- Layer 3: planted-regression fixtures ------------------------------------


def _fixture_entry(stem: str, name: str = "fixture.cost"):
    sys.path.insert(0, COST_FIXTURES)
    try:
        mod = __import__(stem)
    finally:
        sys.path.pop(0)
    return contracts.Contract(
        name=name, make=mod.make, base_symbols=mod.BASE_SYMBOLS,
        cost_scales=(1, 2),
    )


@pytest.fixture(scope="module")
def clean_lock(tmp_path_factory):
    """A lockfile baselined from the CLEAN fixture twin."""
    entry = costmodel.trace_entry(_fixture_entry("cost_clean"))
    fp = {"fixture.cost": cost_contracts.fingerprint(entry)}
    path = str(tmp_path_factory.mktemp("costs") / "COSTS.json")
    cost_contracts.write_lockfile(fp, path)
    return path


def _diff_fixture(stem: str, clean_lock: str, **trace_kw):
    entry = costmodel.trace_entry(_fixture_entry(stem), **trace_kw)
    live = {"fixture.cost": cost_contracts.fingerprint(entry)}
    lock = cost_contracts.load_lockfile(clean_lock)
    return entry, cost_contracts.diff_costs(live, lock, "cpu")


def test_clean_fixture_round_trips(clean_lock):
    _, diff = _diff_fixture("cost_clean", clean_lock)
    assert diff.ok, diff.violations


def test_planted_dense_pair_caught(clean_lock):
    entry, diff = _diff_fixture("cost_dense_pair", clean_lock)
    assert not diff.ok
    # The diff names the drifting primitives (the planted einsum).
    assert any("dot_general" in v for v in diff.violations), diff.violations
    # And the quantitative contract sees the O(T*S^2) tensor directly.
    bad = entry.dense_pair_eqns(n_states=8)
    assert bad, "dense-pair detector missed the planted [T,8,8] op"
    assert any(c.out_elems >= 32 * entry.geometries[-1] for c in bad)


def test_clean_fixture_has_no_dense_pair():
    entry = costmodel.trace_entry(_fixture_entry("cost_clean"))
    assert entry.dense_pair_eqns(n_states=8) == []


def test_dense_pair_detector_sees_inside_scan_bodies():
    """A dense per-step [S, S] op hidden inside a T-trip scan does O(T*S^2)
    total work while each application outputs only S^2 elements — the
    detector must count it at full loop multiplicity, not one application."""
    import numpy as np

    def make(scale: int = 1):
        T = 1024 * scale
        obs = jnp.asarray(np.arange(T, dtype=np.int32) % 4)

        def fn(o):
            def body(carry, x):
                step = jnp.ones((8, 8), jnp.float32) * x.astype(jnp.float32)
                new = jnp.max(step + carry[None, :], axis=1)
                return new, new[0]

            carry, ys = jax.lax.scan(body, jnp.zeros(8, jnp.float32), o)
            return carry.sum() + ys.sum()

        return fn, (obs,)

    c = contracts.Contract(
        name="fixture.scan-dense", make=make, base_symbols=1024,
        cost_scales=(1, 2),
    )
    entry = costmodel.trace_entry(c)
    bad = entry.dense_pair_eqns(n_states=8)
    assert bad, "per-step dense pair op inside the scan body was missed"
    assert all(b.path.startswith("scan/") for b in bad)


def test_planted_regrown_pass_caught(clean_lock):
    """The r9 anti-regression: a de-fused backward re-appearing as its own
    T-scaling pass must fail CI with the pass named — both through the
    lockfile diff (scan count) and the pass-count pin."""
    entry, diff = _diff_fixture("cost_regrown_pass", clean_lock)
    assert not diff.ok
    # The diff NAMES the regrown pass: the T-scaling pass count violation,
    # with the drifting primitives attached.
    assert any(
        "pass count 1 -> 2" in v and "drifting prims" in v
        for v in diff.violations
    ), diff.violations
    # And the pass counter itself sees 2 T-scaling passes where the clean
    # (fused) baseline has 1 — the quantity EXPECTED_PASSES pins.
    clean_entry = costmodel.trace_entry(_fixture_entry("cost_clean"))
    assert clean_entry.passes() == 1
    assert entry.passes() == 2


def test_planted_regrown_products_caught(clean_lock):
    """The ISSUE 17 anti-regression: a de-folded standalone PRODUCTS pass
    (per-step [2,2] matrix composition as its own launch) re-appearing next
    to the co-scheduled chain must fail CI with the regrown scan named —
    the same double gate as the r9 twin."""
    entry, diff = _diff_fixture("cost_regrown_products", clean_lock)
    assert not diff.ok
    assert any(
        "pass count 1 -> 2" in v and "drifting prims" in v
        for v in diff.violations
    ), diff.violations
    clean_entry = costmodel.trace_entry(_fixture_entry("cost_clean"))
    assert clean_entry.passes() == 1
    assert entry.passes() == 2


def test_planted_double_scan_caught(clean_lock):
    _, diff = _diff_fixture("cost_double_scan", clean_lock)
    assert not diff.ok
    # Doubled trip count doubles the serial-depth slope AND the scan flops.
    assert any("serial_depth" in v for v in diff.violations), diff.violations
    assert any("scan" in v or "eqn count" in v for v in diff.violations)


def test_planted_fixed_epilogue_caught(clean_lock):
    _, diff = _diff_fixture("cost_fixed_epilogue", clean_lock)
    assert not diff.ok
    # The regression is FIXED cost: flops.fixed drifts, dot_general named.
    assert any(
        "flops.fixed" in v and "dot_general" in v for v in diff.violations
    ), diff.violations


def test_planted_f64_caught(clean_lock):
    with jax.experimental.enable_x64():
        entry, diff = _diff_fixture("cost_f64", clean_lock)
        assert not diff.ok
        # Doubled stream bytes, convert_element_type in the histogram diff.
        assert any("bytes" in v for v in diff.violations), diff.violations
        # The boolean layer catches the dtype itself on the same trace.
        fn, args = _fixture_entry("cost_f64").make(1)[:2]
        info = contracts.inspect_jaxpr(jax.make_jaxpr(fn)(*args))
        assert info["bad_dtypes"], "no-f64 detector missed the planted upcast"


def test_stale_lockfile_entry_reported(clean_lock):
    # An empty live registry leaves the clean entry stale — reported like
    # a stale waiver (note + stale list), not silently dropped.
    lock = cost_contracts.load_lockfile(clean_lock)
    diff = cost_contracts.diff_costs({}, lock, "cpu")
    assert diff.stale == ["fixture.cost"]
    assert any("stale lockfile entry" in n for n in diff.notes)


def test_missing_lockfile_entry_is_violation(clean_lock):
    entry = costmodel.trace_entry(_fixture_entry("cost_clean", "fixture.new"))
    live = {"fixture.new": cost_contracts.fingerprint(entry)}
    lock = cost_contracts.load_lockfile(clean_lock)
    diff = cost_contracts.diff_costs(live, lock, "cpu")
    assert not diff.ok
    assert any("not in the lockfile" in v for v in diff.violations)


def test_missing_platform_section_is_note_not_violation(clean_lock):
    lock = cost_contracts.load_lockfile(clean_lock)
    diff = cost_contracts.diff_costs({}, lock, "tpu")
    assert diff.ok
    assert any("no 'tpu' section" in n for n in diff.notes)


# -- Layer 5: the mem pass on the tree ---------------------------------------


@pytest.fixture(scope="module")
def mem_report():
    return mem_contracts.run_mem_pass()


# The tree-wide mem pass re-traces the whole registry (~30 s) — slow-
# marked like the graftcost CLI round trip; it still gates every
# ci_checks.sh run (`--no-lint --mem`), plain `pytest tests/`, and
# __graft_entry__'s self-check.  The planted-fixture detector proofs
# below stay in tier-1 (small traces).
@pytest.mark.slow
def test_mem_pass_green_on_tree(mem_report):
    assert mem_report["ok"], {
        "diff": mem_report["diff"]["violations"],
        "contracts": [
            (r["name"], r["violations"])
            for r in mem_report["contracts"] if not r["ok"]
        ],
    }
    # The committed lockfile covers the whole registry (the cost cast +
    # the fused-EM loop + the blocked island reduction) — no stale
    # entries, nothing unbaselined.
    assert mem_report["diff"]["stale"] == []
    assert mem_report["diff"]["checked"] >= 19


@pytest.mark.slow
def test_mem_contracts_all_present(mem_report):
    names = {r["name"] for r in mem_report["contracts"]}
    assert names == {
        "mem.vmem-budget", "mem.no-linear-temps", "mem.seq-shard-budget",
        "mem.stacked-envelope",
    }


@pytest.mark.slow
def test_mem_island_entry_has_no_linear_temps(mem_report):
    byname = {r["name"]: r for r in mem_report["contracts"]}
    notes = byname["mem.no-linear-temps"]["notes"]
    assert notes["island_linear_groups"] == []
    # The fused-EM body's per-symbol working set sits well under the pin.
    assert 0 < notes["em_body_peak_bps"] < mem_contracts.EM_BODY_BPS_MAX


# -- Layer 5: planted-regression fixtures ------------------------------------


def _mem_fixture_entry(stem: str, name: str = "fixture.mem"):
    sys.path.insert(0, COST_FIXTURES)
    try:
        mod = __import__(stem)
    finally:
        sys.path.pop(0)
    return contracts.Contract(
        name=name, make=mod.make, base_symbols=mod.BASE_SYMBOLS,
        cost_scales=(1, 2),
    )


@pytest.fixture(scope="module")
def clean_mem_lock(tmp_path_factory):
    """A MEMORY.json baselined from the CLEAN blocked-reduction twin."""
    entry = mem_contracts.trace_mem_entry(_mem_fixture_entry("mem_clean"))
    fp = {"fixture.mem": mem_contracts.fingerprint(entry)}
    path = str(tmp_path_factory.mktemp("mem") / "MEMORY.json")
    mem_contracts.write_lockfile(fp, path)
    return path


def _mem_diff_fixture(stem: str, clean_lock: str):
    entry = mem_contracts.trace_mem_entry(_mem_fixture_entry(stem))
    live = {"fixture.mem": mem_contracts.fingerprint(entry)}
    lock = mem_contracts.load_lockfile(clean_lock)
    return entry, mem_contracts.diff_mem(live, lock, "cpu")


def test_clean_mem_fixture_round_trips(clean_mem_lock):
    entry, diff = _mem_diff_fixture("mem_clean", clean_mem_lock)
    assert diff.ok, diff.violations
    # The blocked twin materializes nothing that scales with T.
    assert entry.linear_groups() == []


def test_planted_whole_record_island_temp_caught(clean_mem_lock):
    """The r4 island-OOM class: the whole-record twin's s32[T] temps must
    fail the lockfile diff NAMING the offending allocation group, and the
    liveness detector must see the s32 4 B/symbol slope directly."""
    entry, diff = _mem_diff_fixture("mem_linear_temp", clean_mem_lock)
    assert not diff.ok
    assert any(
        "O(T) allocation groups drifted" in v and "mem_linear_temp.py" in v
        for v in diff.violations
    ), diff.violations
    bad = entry.linear_groups()
    assert bad, "liveness detector missed the planted s32[T] temps"
    assert all("mem_linear_temp.py" in g for g, _ in bad)
    # s32 whole-record temps: at least the 4 B/symbol class, several of
    # them — the clean twin's blocked scan keeps all of this O(block_w).
    assert max(bps for _, bps in bad) >= 4.0
    # And the peak-liveness slope grew accordingly vs the blocked twin.
    clean = mem_contracts.trace_mem_entry(_mem_fixture_entry("mem_clean"))
    assert (
        entry.fits()["peak_bytes"].per_symbol
        > clean.fits()["peak_bytes"].per_symbol + 4.0
    )


def test_planted_oversize_lanes_fails_naming_buffers():
    sys.path.insert(0, COST_FIXTURES)
    try:
        import importlib

        fx = importlib.import_module("mem_oversize_lanes")
    finally:
        sys.path.pop(0)
    f = memmodel.feasible(fx.KERNEL, fx.KNOBS)
    assert not f.ok
    names = {b.name for b in f.offenders}
    assert {"aprev_full", "wz_full"} & names, f.offenders
    assert "aprev_full" in f.reason or "wz_full" in f.reason
    # One lane notch down is feasible — the pick_lane_T cap.
    assert memmodel.feasible(fx.KERNEL, fx.KNOBS.replace(lane_T=65536)).ok


def test_planted_stacked_overflow_fails_naming_buffers():
    sys.path.insert(0, COST_FIXTURES)
    try:
        import importlib

        fx = importlib.import_module("mem_stacked_overflow")
    finally:
        sys.path.pop(0)
    f = memmodel.feasible(fx.KERNEL, fx.KNOBS)
    assert not f.ok
    assert "dmax_out" in {b.name for b in f.offenders}, f.offenders
    assert "dmax_out" in f.reason
    # The guard's derived block cap restores feasibility at M=3.
    cap = memmodel.stacked_block_cap(3, scores=True)
    assert memmodel.feasible(
        fx.KERNEL, fx.KNOBS.replace(block_size=cap)
    ).ok


def test_attribution_table_names_fixed_cost_groups():
    # The em.seq.onehot attribution table is the BASELINE.md size-curve
    # decomposition: it must name the boundary/prep/epilogue groups that
    # carry the fixed cost.
    entries = {c.name: c for c in cost_contracts.cost_entries()}
    traced = costmodel.trace_entry(entries["em.seq.onehot"])
    table = costmodel.attribution_table(traced)
    assert "fb_onehot.py:step" in table
    assert "| **total** |" in table
    att = costmodel.attribute(traced)
    assert att["groups"], "no attribution groups"
    totals = att["totals"]
    assert totals["flops"]["per_symbol"] > 100  # the real per-symbol work
    assert totals["flops"]["fixed"] < totals["flops"]["per_symbol"] * 1e5
