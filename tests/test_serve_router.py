"""Pod-scale routing tier (PR 20): N per-host brokers behind one
RequestRouter, certified the way the fleet was in PR 15 — seeded,
deterministic host-level chaos with output BIT-IDENTICAL to the
fault-free run, zero dropped admitted requests, and zero duplicate
executions (journal-verified).

Layers:

- unit: the HostHealth state machine (DeviceHealth one fault-domain
  level up, plus terminal DEAD) on an injected clock; the broker's
  measured-flush-wall ``retry_after_s`` hint (monotone in queue depth,
  capped, floored — the load-shedding contract).
- routing: least-loaded placement across two in-process hosts,
  bit-identical to the single-broker batch run; all-hosts-saturated
  shedding with the minimum machine-readable retry hint; quarantined
  hosts DRAIN their admitted queue while routing sheds around them, and
  the half-open probe restores them.
- chaos: the acceptance scenario — one host SIGKILLed mid-flush during
  a mixed multi-tenant run; the survivor adopts every journaled
  admission off the dead host's write-ahead journal, results
  bit-identical, the dead host's restart finds ZERO incomplete admits
  (the superseding rule), and graftscope lineage shows both host
  memberships for every failed-over request.  Plus the seeded
  ``faultplan.host_matrix`` swept over seeds, and the
  admit-without-queue-visibility edge (host dies between journal.admit
  and queue visibility).
- wire: the mux+router stress under the graftsync LockTracker, and
  tools/serve_client rotating to an alternate ``--connect`` endpoint
  (AF_UNIX -> TCP side door) across a mid-stream connection death.
"""

import json
import os
import socket as socket_mod
import sys
import threading
import time

import numpy as np
import pytest

from cpgisland_tpu import obs, pipeline, resilience
from cpgisland_tpu.analysis import tracksync
from cpgisland_tpu.models import presets
from cpgisland_tpu.obs import scope as scope_mod
from cpgisland_tpu.resilience import RetryPolicy, faultplan
from cpgisland_tpu.resilience.faultplan import Fault, FaultPlan, ManualClock
from cpgisland_tpu.resilience.manifest import RunManifest
from cpgisland_tpu.serve import (
    Backpressure,
    BrokerConfig,
    RequestBroker,
    Session,
)
from cpgisland_tpu.serve.router import (
    DEAD,
    HostHealth,
    RequestRouter,
    RouterConfig,
    RouterHost,
)

FAST = RetryPolicy(backoff_base_s=0.0)


@pytest.fixture(autouse=True)
def _fresh_resilience_state():
    resilience.reset()  # also disarms any leaked graftfault plan
    yield
    resilience.reset()


@pytest.fixture()
def tracker():
    # Composes with CPGISLAND_TRACKSYNC=1 (the ci_checks router slice
    # runs this file under the session-wide tracker; uninstall is a
    # no-op there), else installs one for the test's duration.
    tr, uninstall = tracksync.ensure_installed()
    try:
        yield tr
    finally:
        uninstall()


def _gen_symbols(rng, n: int) -> np.ndarray:
    bg = rng.choice(4, size=n, p=[0.3, 0.2, 0.2, 0.3])
    k = max(1, n // 4)
    bg[:k] = rng.choice(4, size=k, p=[0.1, 0.4, 0.4, 0.1])
    return bg.astype(np.uint8)


def _requests(seed=7, n=8):
    """Mixed multi-tenant workload: decode + posterior, two tenants."""
    rng = np.random.default_rng(seed)
    return [
        (
            i,
            f"rec{i}",
            "decode" if i % 3 else "posterior",
            "a" if i % 2 else "b",
            _gen_symbols(rng, 600 + 137 * i),
        )
        for i in range(n)
    ]


def _calls_key(calls) -> list:
    if calls is None:
        return []
    return [
        (int(calls.beg[i]), int(calls.end[i]), int(calls.length[i]),
         float(calls.gc_content[i]), float(calls.oe_ratio[i]))
        for i in range(len(calls))
    ]


def _result_key(r) -> tuple:
    return (r.kind, _calls_key(r.calls),
            None if r.conf_sum is None else float(r.conf_sum).hex())


def _assert_results_identical(got: dict, want: dict) -> None:
    assert set(got) == set(want)
    for rid in want:
        assert got[rid].ok, (rid, got[rid].error)
        assert _result_key(got[rid]) == _result_key(want[rid]), rid


def _batch_truth(recs) -> dict:
    """Single-broker single-flush ground truth (no router geometry)."""
    params = presets.durbin_cpg8()
    sess = Session(params, name="truth", private_breaker=True)
    b = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 20, flush_deadline_s=0.0)
    )
    for rid, nm, kind, tenant, syms in recs:
        b.submit(request_id=rid, tenant=tenant, kind=kind, symbols=syms,
                 name=nm)
    out = {r.id: r for r in b.drain()}
    b.close()
    assert all(r.ok for r in out.values())
    return out


def _mk_hosts(tmp=None, *, manifest=True, flush_symbols=1500,
              broker_cfg=None) -> list:
    params = presets.durbin_cpg8()
    hosts = []
    for label in ("host0", "host1"):
        sess = Session(params, name=label, private_breaker=True,
                       retry_policy=FAST)
        cfg = broker_cfg or BrokerConfig(
            flush_symbols=flush_symbols, flush_deadline_s=0.01
        )
        kw = {}
        if manifest:
            tmp.mkdir(parents=True, exist_ok=True)
            kw["manifest_path"] = str(tmp / f"{label}.journal.jsonl")
        hosts.append(RouterHost(label, RequestBroker(sess, cfg, **kw)))
    return hosts


def _run_router(recs, *, plan=None, tmp=None, manifest=True,
                config=None, timeout_s=300.0):
    """Run ``recs`` through a 2-host router; returns ({id: result},
    router, observed events, [ids whose submit was SIGKILLed]).

    Every request is submitted BEFORE the workers start, so the
    least-loaded placement is deterministic.  A kill escaping ``submit``
    (the admitted-but-never-queued edge) is caught, the victim host is
    identified by which journal holds the unacked admit, and
    ``fail_host`` runs the synchronous failover — delivery of EVERY
    admitted id is still required.  Exactly-once delivery is asserted.
    """
    hosts = _mk_hosts(tmp, manifest=manifest)
    clock = ManualClock()
    cfg = config or RouterConfig(
        cooldown_s=30.0, idle_wait_s=0.01, failover_retry_s=0.01,
        now_fn=clock,
    )
    router = RequestRouter(hosts, cfg)
    results: dict = {}
    delivered: list = []
    done = threading.Event()

    def on_result(r):
        delivered.append(r.id)
        results[r.id] = r
        if len(results) >= len(recs):
            done.set()

    killed: list = []
    ctx = faultplan.active(plan) if plan is not None else None
    if ctx is not None:
        ctx.__enter__()
    try:
        with obs.observe() as ob:
            for rid, nm, kind, tenant, syms in recs:
                try:
                    router.submit(request_id=rid, tenant=tenant, kind=kind,
                                  symbols=syms, name=nm)
                except faultplan.SimulatedKill:
                    killed.append(rid)
            router.start(on_result)
            if killed:
                victim = None
                for h in hosts:
                    if h.broker.manifest is None:
                        continue
                    pend = {
                        int(rec["index"]) for rec in
                        RunManifest.scan_incomplete(h.broker.manifest.path)
                    }
                    if pend & set(killed):
                        victim = h.label
                        break
                assert victim is not None, "unacked admit in no journal"
                router.fail_host(victim, "admit-kill")
            deadline = time.monotonic() + timeout_s
            while not done.wait(timeout=0.25):
                assert time.monotonic() < deadline, (
                    f"undelivered: "
                    f"{sorted(set(r[0] for r in recs) - set(results))}, "
                    f"stats={router.stats()}"
                )
                clock.advance(5.0)
    finally:
        router.stop()
        router.close()
        router.release()
        if ctx is not None:
            ctx.__exit__(None, None, None)
    assert len(delivered) == len(set(delivered)), (
        f"duplicate deliveries: {sorted(delivered)}"
    )
    return results, router, list(ob.events), killed


def _journal_lines(path: str) -> list:
    return [json.loads(ln) for ln in open(path)]


# ---------------------------------------------------------------------------
# Unit: HostHealth state machine on an injected clock


def test_host_health_full_cycle_on_manual_clock():
    clock = ManualClock()
    h = HostHealth("hX", fault_threshold=3, cooldown_s=30.0, now_fn=clock)
    assert h.state() == "healthy" and h.can_serve()
    h.record_fault(OSError("conn reset"))
    assert h.state() == "suspect" and h.can_serve()
    h.record_success()
    assert h.state() == "healthy"  # consecutive-evidence: success clears
    for i in range(3):
        h.record_fault(OSError(f"f{i}"))
    assert h.state() == "quarantined"
    assert not h.can_serve() and h.eta_s() == pytest.approx(30.0)
    clock.advance(29.0)
    assert not h.can_serve()
    clock.advance(1.5)
    assert h.can_serve()  # flips to the half-open probe
    assert h.state() == "probing"
    h.record_fault(OSError("probe bounce"))
    assert h.state() == "quarantined"  # probe failure re-quarantines
    assert h.snapshot()["quarantines"] == 2
    clock.advance(31.0)
    assert h.can_serve() and h.state() == "probing"
    h.record_success()
    assert h.state() == "healthy"
    assert h.snapshot()["restores"] == 1


def test_host_health_divergence_backpressure_and_dead():
    clock = ManualClock()
    # Journal divergence is corruption evidence: default threshold 1.
    hd = HostHealth("hd", divergence_threshold=1, now_fn=clock)
    hd.record_divergence("key mismatch")
    assert hd.state() == "quarantined"
    assert hd.snapshot()["divergences"] == 1

    # Backpressure strikes quarantine out of the ROUTING rotation only.
    hb = HostHealth("hb", backpressure_threshold=2, now_fn=clock)
    hb.record_backpressure()
    assert hb.state() == "suspect"
    hb.record_backpressure()
    assert hb.state() == "quarantined"

    # DEAD is terminal: nothing serves, eta is infinite, idempotent.
    h = HostHealth("hx", now_fn=clock)
    h.mark_dead("worker raised SimulatedKill")
    assert h.state() == DEAD and not h.can_serve()
    assert h.eta_s() == float("inf")
    h.record_fault(OSError("late"))
    h.record_backpressure()
    h.record_success()
    h.mark_dead("again")
    snap = h.snapshot()
    assert snap["state"] == DEAD
    assert snap["dead_reason"] == "worker raised SimulatedKill"

    # The operator drain hook.
    hq = HostHealth("hq", now_fn=clock)
    hq.force_quarantine("drain")
    assert hq.state() == "quarantined"


# ---------------------------------------------------------------------------
# Unit: the measured-flush-wall retry_after_s load-shedding hint


def test_retry_after_monotone_in_depth_and_tracks_measured_wall():
    params = presets.durbin_cpg8()
    sess = Session(params, name="hint", private_breaker=True)
    b = RequestBroker(
        sess, BrokerConfig(flush_symbols=1000, flush_deadline_s=0.02)
    )
    try:
        # Empty histogram: the static deadline heuristic, floored/capped.
        hints = []
        for q in (0, 500, 1000, 5000, 50_000, 10**7):
            b._queued_symbols = q
            hints.append(b._retry_after_locked())
        assert hints == sorted(hints)  # monotone in queue depth
        assert hints[0] == 0.05  # floor: clients never busy-loop
        assert hints[-1] == 5.0  # cap: clients never park forever
        b._queued_symbols = 5000
        static = b._retry_after_locked()
        assert static == pytest.approx(5 * 0.02)

        # A measured wall wider than the deadline must widen the hint:
        # the deadline only sets when a flush OPENS, the wall is what a
        # flush actually costs to drain.
        for _ in range(4):
            b._flush_wall.observe(0.8)
        measured = b._retry_after_locked()
        assert measured > static
        assert measured == pytest.approx(5 * 0.8)
        hints2 = []
        for q in (0, 1000, 5000, 50_000):
            b._queued_symbols = q
            hints2.append(b._retry_after_locked())
        assert hints2 == sorted(hints2)  # still monotone, measured arm
        b._queued_symbols = 0

        # The real admission path carries the hint on the wire exception.
        small = RequestBroker(
            sess, BrokerConfig(flush_symbols=1 << 20,
                               flush_deadline_s=0.01,
                               tenant_max_requests=1),
        )
        syms = _gen_symbols(np.random.default_rng(2), 300)
        small.submit(request_id=1, tenant="a", kind="decode",
                     symbols=syms, name="r1")
        with pytest.raises(Backpressure) as ei:
            small.submit(request_id=2, tenant="a", kind="decode",
                         symbols=syms, name="r2")
        assert ei.value.reason == "tenant_requests"
        assert ei.value.retry_after_s >= 0.05
        small.drain()
        small.close()
    finally:
        b.close()


# ---------------------------------------------------------------------------
# Routing: least-loaded placement, elastic shedding, quarantine drain


@pytest.mark.slow
def test_least_loaded_two_host_routing_bit_identical(tmp_path):
    recs = _requests()
    want = _batch_truth(recs)
    got, router, _events, killed = _run_router(recs, manifest=False)
    assert killed == []
    _assert_results_identical(got, want)
    st = router.stats()
    # Least-loaded placement really spread the pre-start submissions.
    assert st["hosts"]["host0"]["flushes"] >= 1
    assert st["hosts"]["host1"]["flushes"] >= 1
    for label in ("host0", "host1"):
        ent = st["hosts"][label]
        assert ent["queued_requests"] == 0 and ent["queued_symbols"] == 0
        assert ent["health"]["state"] == "healthy"
    assert st["failovers"] == 0 and st["failed_over_requests"] == 0
    assert st["adopted_pending"] == 0 and st["routed_inflight"] == 0


@pytest.mark.slow
def test_all_hosts_saturated_sheds_then_quarantine_drains_and_probes():
    clock = ManualClock()
    hosts = _mk_hosts(manifest=False, broker_cfg=BrokerConfig(
        flush_symbols=1 << 20, flush_deadline_s=0.01,
        tenant_max_requests=2,
    ))
    router = RequestRouter(hosts, RouterConfig(
        backpressure_threshold=1, cooldown_s=30.0, idle_wait_s=0.01,
        now_fn=clock,
    ))
    rng = np.random.default_rng(19)
    recs = [(i, f"s{i}", "decode", "a", _gen_symbols(rng, 400 + 90 * i))
            for i in range(5)]
    results: dict = {}

    def on_result(r):
        results[r.id] = r

    def wait_for(n, timeout_s=180.0):
        deadline = time.monotonic() + timeout_s
        while len(results) < n:
            assert time.monotonic() < deadline, (
                sorted(results), router.stats()
            )
            time.sleep(0.05)

    try:
        with obs.observe() as ob:
            for rid, nm, kind, tenant, syms in recs[:4]:
                router.submit(request_id=rid, tenant=tenant, kind=kind,
                              symbols=syms, name=nm)
            # Both hosts at their tenant cap: the shed is machine-readable
            # (reason + the MINIMUM of the per-host measured-wall hints).
            with pytest.raises(Backpressure) as ei:
                router.submit(request_id=4, tenant="a", kind="decode",
                              symbols=recs[4][4], name="s4")
            assert ei.value.reason == "all_hosts_saturated"
            assert ei.value.retry_after_s == pytest.approx(0.05)
            # One strike each at threshold 1: both hosts quarantined; a
            # fresh submit now finds NO serveable host and the hint is
            # the remaining cooldown (capped).
            for h in hosts:
                assert h.health.state() == "quarantined"
            with pytest.raises(Backpressure) as ei2:
                router.submit(request_id=4, tenant="a", kind="decode",
                              symbols=recs[4][4], name="s4")
            assert ei2.value.reason == "no_healthy_host"
            assert ei2.value.retry_after_s == pytest.approx(5.0)
            assert router.backpressure()

            # Drain-via-quarantine: the workers complete every admitted
            # request while routing sheds around both hosts.
            router.start(on_result)
            wait_for(4)
            assert all(results[r[0]].ok for r in recs[:4])

            # Cooldown elapses -> half-open probe admission -> restore.
            clock.advance(31.0)
            router.submit(request_id=4, tenant="a", kind="decode",
                          symbols=recs[4][4], name="s4")
            wait_for(5)
            assert results[4].ok
    finally:
        router.stop()
        router.close()
        router.release()
    assert sum(h.health.snapshot()["restores"] for h in hosts) == 1
    quar = [e for e in ob.events if e["event"] == "host_quarantined"]
    assert len(quar) == 2
    assert all(e["reason"] == "backpressure" for e in quar)
    assert any(e["event"] == "host_restored" for e in ob.events)


# ---------------------------------------------------------------------------
# Reused ids across hosts: replay affinity + visible duplicate arbitration


def test_reused_id_replays_on_owner_and_collision_stays_visible(tmp_path):
    hosts = _mk_hosts(tmp_path, broker_cfg=BrokerConfig(
        flush_symbols=1 << 20, flush_deadline_s=0.0
    ))
    router = RequestRouter(hosts, RouterConfig(idle_wait_s=0.01))
    b0, b1 = hosts[0].broker, hosts[1].broker
    syms = _gen_symbols(np.random.default_rng(23), 500)
    try:
        router.submit(request_id=9, tenant="a", kind="decode",
                      symbols=syms, name="A")
        (first,) = router.drain()
        assert first.id == 9 and first.ok and not first.replayed

        # Identical identity: replay AFFINITY routes it back to the host
        # whose journal completed it — zero device work pod-wide.
        before = (b0.flushed_symbols, b1.flushed_symbols)
        router.submit(request_id=9, tenant="a", kind="decode",
                      symbols=syms, name="A")
        (again,) = router.drain()
        assert again.replayed and again.route == "replay"
        assert _result_key(again) == _result_key(first)
        assert (b0.flushed_symbols, b1.flushed_symbols) == before

        # A reused id with a DIFFERENT identity lands on the owning
        # host's arbitration and the rejection stays visible through the
        # router (never silently re-executed as a second copy).
        with pytest.raises(ValueError, match="duplicate request id"):
            router.submit(request_id=9, tenant="a", kind="decode",
                          symbols=syms, name="B")
    finally:
        router.close()
        router.release()


# ---------------------------------------------------------------------------
# Chaos: the acceptance scenario — host SIGKILL mid-flush


@pytest.mark.slow
# The worker thread re-raises SimulatedKill by contract (SIGKILL: nothing
# else may run on the dead host) — pytest's thread-exception warning is
# the expected trace of that, not a leak.
@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_host_sigkill_mid_flush_fails_over_bit_identical(tmp_path):
    """One host SIGKILLed mid-flush during a mixed multi-tenant run: the
    surviving host completes every admitted request bit-identically to
    the fault-free run, zero drops, zero duplicate executions
    (journal-verified), and each failed-over request's lineage shows
    BOTH host memberships."""
    recs = _requests()
    sizes = {r[0]: int(r[4].size) for r in recs}
    clean, _r0, _e0, _k0 = _run_router(recs, tmp=tmp_path / "clean")

    plan = FaultPlan(
        [Fault("flush.enter", kind="kill", nth=1, match="@host0")],
        name="host0-midflush-kill",
    )
    sc = scope_mod.install(
        scope_mod.Scope(flight_path=str(tmp_path / "router.flight.json"))
    )
    try:
        chaos, router, events, killed = _run_router(
            recs, plan=plan, tmp=tmp_path / "chaos"
        )
    finally:
        scope_mod.uninstall(sc)
    assert killed == []  # the kill fired in host0's worker, not submit
    _assert_results_identical(chaos, clean)

    # Zero duplicate executions, ledger-side: host0 finished NOTHING (a
    # killed flush never reaches finish_flush), the survivor executed
    # every symbol exactly once.
    b0 = router._host_by_label["host0"].broker
    b1 = router._host_by_label["host1"].broker
    assert b0.flushed_symbols == 0
    assert b1.flushed_symbols == sum(sizes.values())

    # Lineage: every request closed ok; the failed-over ones crossed
    # host0 -> host1 with the failover marker on the second membership.
    traces = {tr["id"]: tr for tr in sc.traces}
    assert sorted(traces) == sorted(sizes)  # zero drops
    adopted = {rid for rid, tr in traces.items()
               if tr.get("hosts") == ["host0", "host1"]}
    assert adopted
    for rid, tr in traces.items():
        assert tr["ok"], rid
        if rid in adopted:
            hh = [h for h in tr["hops"] if h["hop"] == "host"]
            assert [h["host"] for h in hh] == ["host0", "host1"]
            assert hh[0].get("failover") is None
            assert hh[1].get("failover") is True
        else:
            assert tr.get("hosts") == ["host1"]
    # The killed flush's members carry BOTH flush memberships (the
    # flush.enter hop lands before the kill point by contract).
    assert any(
        len([h for h in traces[rid]["hops"] if h["hop"] == "flush.enter"])
        >= 2
        for rid in adopted
    )

    # Events + flight recorder: death, failover, adoption all visible.
    assert len([e for e in events
                if e["event"] == "graftfault_injected"]) == 1
    died = [e for e in events if e["event"] == "host_died"]
    assert died and died[0]["host"] == "host0"
    fo = [e for e in events if e["event"] == "host_failover"]
    assert len(fo) == 1 and fo[0]["host"] == "host0"
    assert fo[0]["n_adopted"] == fo[0]["n_pending"] == len(adopted)
    ring = sc.recorder.snapshot()
    kinds = {e["kind"] for e in ring}
    assert {"host_died", "host_failover", "journal_adopted"} <= kinds
    assert {e["id"] for e in ring
            if e["kind"] == "journal_adopted"} == adopted
    st = router.stats()
    assert st["failovers"] == 1
    assert st["failed_over_requests"] == len(adopted)
    assert st["adopted_pending"] == 0
    assert st["hosts"]["host0"]["health"]["state"] == DEAD

    # The superseding rule on disk: the adopted completions landed in
    # the DEAD host's journal, so its restart finds zero incomplete
    # admits and a reconnecting client's re-submission REPLAYS with
    # zero device work.
    p0 = str(tmp_path / "chaos" / "host0.journal.jsonl")
    assert RunManifest.scan_incomplete(p0) == []
    lines = _journal_lines(p0)
    for rid in adopted:
        assert sum(1 for ln in lines if ln.get("kind") == "admit"
                   and ln.get("index") == rid) == 1
        assert sum(1 for ln in lines if ln.get("kind") == "record"
                   and ln.get("index") == rid) == 1
    params = presets.durbin_cpg8()
    sess = Session(params, name="host0-restart", private_breaker=True)
    b_r = RequestBroker(
        sess, BrokerConfig(flush_symbols=1500, flush_deadline_s=0.01),
        manifest_path=p0, resume=True,
    )
    assert b_r.drain() == []  # nothing re-executes on restart
    rid = min(adopted)
    _i, nm, kind, tenant, syms = recs[rid]
    b_r.submit(request_id=rid, tenant=tenant, kind=kind, symbols=syms,
               name=nm)
    (rr,) = b_r.drain()
    assert rr.replayed and rr.route == "replay"
    assert b_r.flushed_symbols == 0
    assert _result_key(rr) == _result_key(clean[rid])
    b_r.close()
    b_r.release()


@pytest.mark.slow
@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
@pytest.mark.parametrize("seed", [0, 1])
def test_seeded_host_chaos_matrix_converges_bit_identical(seed, tmp_path):
    """The seeded host-chaos matrix: mid-flush kill, pre-flush host kill,
    transport partition, and the admit-unacked kill — interleaving-
    invariant assertions only: bit-identity, zero drops, exactly-once
    delivery (asserted inside the harness), every injection ledgered."""
    recs = _requests(seed=17, n=8)
    want = _batch_truth(recs)
    for plan in faultplan.host_matrix(seed):
        d = tmp_path / plan.name
        chaos, _router, events, _killed = _run_router(
            recs, plan=plan, tmp=d
        )
        _assert_results_identical(chaos, want)
        injected = [e for e in events
                    if e["event"] == "graftfault_injected"]
        assert len(injected) == len(plan.injected)


@pytest.mark.slow
def test_host_death_between_admit_and_queue_visibility(tmp_path):
    """The sharpest journal edge: the host dies AFTER the admit line
    lands but BEFORE the request is visible to any flush consumer.  No
    worker will ever execute it — only the cross-host failover can.
    Zero drops, zero double executions."""
    recs = _requests(seed=31, n=3)
    want = _batch_truth(recs)
    hosts = _mk_hosts(tmp_path)
    router = RequestRouter(
        hosts, RouterConfig(idle_wait_s=0.01, failover_retry_s=0.01)
    )
    plan = FaultPlan(
        [Fault("journal.post_admit", kind="kill", nth=1, match="req2")],
        name="admit-unacked-kill",
    )
    try:
        with faultplan.active(plan):
            for rid, nm, kind, tenant, syms in recs[:2]:
                router.submit(request_id=rid, tenant=tenant, kind=kind,
                              symbols=syms, name=nm)
            # rid 2 routes least-loaded to host0; the kill fires between
            # its journal line and queue visibility.
            with pytest.raises(faultplan.SimulatedKill):
                router.submit(request_id=2, tenant=recs[2][3],
                              kind=recs[2][2], symbols=recs[2][4],
                              name=recs[2][1])
        assert len(plan.injected) == 1
        p0 = hosts[0].broker.manifest.path
        pend = {int(r["index"])
                for r in RunManifest.scan_incomplete(p0)}
        assert pend == {0, 2}  # rid0 queued-incomplete + rid2 unacked

        router.fail_host("host0", "admit-kill")
        out = {r.id: r for r in router.drain()}
    finally:
        router.close()
        router.release()
    _assert_results_identical(out, want)
    # The dead host executed nothing; its journal is fully superseded.
    assert hosts[0].broker.flushed_symbols == 0
    assert RunManifest.scan_incomplete(p0) == []
    lines = _journal_lines(p0)
    for rid in (0, 2):
        assert sum(1 for ln in lines if ln.get("kind") == "admit"
                   and ln.get("index") == rid) == 1
        assert sum(1 for ln in lines if ln.get("kind") == "record"
                   and ln.get("index") == rid) == 1
    assert hosts[0].health.snapshot()["dead_reason"] == "admit-kill"


# ---------------------------------------------------------------------------
# Wire: mux+router under the LockTracker; client endpoint rotation


def _start_server(target_args, sock_path, kwargs=None):
    from cpgisland_tpu.serve.transport import serve_socket

    t = threading.Thread(
        target=serve_socket, args=target_args, kwargs=kwargs or {},
        name="router-server", daemon=True,
    )
    t.start()
    deadline = time.monotonic() + 30.0
    while not os.path.exists(sock_path):
        assert time.monotonic() < deadline, "server socket never appeared"
        time.sleep(0.01)
    while True:
        try:
            s = socket_mod.socket(socket_mod.AF_UNIX,
                                  socket_mod.SOCK_STREAM)
            s.connect(sock_path)
            s.close()
            break
        except OSError:
            assert time.monotonic() < deadline
            time.sleep(0.05)
    return t


def _send_shutdown(sock_path):
    s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
    s.connect(sock_path)
    s.sendall(b'{"op": "shutdown"}\n')
    s.close()


def _client_session(sock_path, requests):
    s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
    s.connect(sock_path)
    rf = s.makefile("r", encoding="utf-8")
    wf = s.makefile("w", encoding="utf-8")
    want = set()
    for req in requests:
        wf.write(json.dumps(req) + "\n")
        want.add(req["id"])
    wf.flush()
    got: dict = {}
    for line in rf:
        obj = json.loads(line)
        if obj.get("id") in want:
            got[obj["id"]] = obj
        if set(got) == want:
            break
    rf.close()
    wf.close()
    s.close()
    return got


BASES = np.array(list("acgt"))


@pytest.mark.slow
def test_mux_over_router_stress_under_tracker(tmp_path, tracker):
    """The mux accept loop fronting a 2-host ROUTER (router duck-types as
    broker AND pool), concurrent clients, under the graftsync runtime
    tracker: zero lock-order or guarded-access violations."""
    hosts = _mk_hosts(manifest=False, flush_symbols=3000)
    router = RequestRouter(hosts, RouterConfig(idle_wait_s=0.02))
    for h in hosts:
        tracker.watch_attrs(
            h.broker, h.broker._lock,
            ["_queued_symbols", "flushes", "flushed_symbols"],
            label=f"RequestBroker[{h.label}]",
        )
    sock_path = str(tmp_path / "router.sock")
    server = _start_server((sock_path, router), sock_path,
                           kwargs={"pool": router})

    rng = np.random.default_rng(43)
    clients = []
    for c in range(2):
        reqs = []
        for k in range(3):
            syms = _gen_symbols(rng, 400 + 170 * k + 60 * c)
            reqs.append({
                "id": c * 100 + k,
                "kind": "decode" if (c + k) % 2 else "posterior",
                "seq": "".join(BASES[syms]),
                "tenant": f"t{c}", "name": f"c{c}r{k}",
            })
        clients.append(reqs)
    results: list = [None, None]
    errors: list = []

    def run_client(c):
        try:
            results[c] = _client_session(sock_path, clients[c])
        except Exception as e:  # surface in the main thread's assert
            errors.append((c, repr(e)))

    threads = [threading.Thread(target=run_client, args=(c,))
               for c in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300.0)
    assert errors == [], errors
    _send_shutdown(sock_path)
    server.join(timeout=60.0)
    assert not server.is_alive()

    for c, reqs in enumerate(clients):
        got = results[c]
        assert got is not None and set(got) == {r["id"] for r in reqs}
        for req in reqs:
            assert got[req["id"]]["ok"], got[req["id"]].get("error")
    tracker.assert_clean()
    assert tracker.summary()["acquires"] > 50
    st = router.stats()
    assert sum(st["hosts"][h]["flushes"] for h in st["hosts"]) >= 2


@pytest.mark.slow
def test_client_rotates_to_alternate_endpoint_across_disconnect(tmp_path):
    """tools/serve_client against a router behind an AF_UNIX door plus a
    TCP side door: a dead first endpoint rotates the client onto the
    alternate at connect time, a mid-stream connection death rotates it
    again, and the re-submission converges to the batch-pipeline
    output."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import serve_client

    from cpgisland_tpu.serve import transport

    params = presets.durbin_cpg8()
    rng = np.random.default_rng(29)
    names_syms = [(f"w{k}", _gen_symbols(rng, 700 + 120 * k))
                  for k in range(4)]
    fa = tmp_path / "w.fa"
    with open(fa, "w") as f:
        for nm, syms in names_syms:
            f.write(f">{nm}\n" + "".join(BASES[syms]) + "\n")
    want = pipeline.decode_file(str(fa), params, compat=False)
    want_text: dict = {}
    for line in want.calls.format_lines().splitlines(keepends=True):
        want_text.setdefault(line.split(" ", 1)[0], []).append(line)

    hosts = _mk_hosts(manifest=False, flush_symbols=1 << 20)
    router = RequestRouter(hosts, RouterConfig(idle_wait_s=0.02))
    tcp_srv = transport._bind_tcp("127.0.0.1", 0)
    port = tcp_srv.getsockname()[1]
    sock_path = str(tmp_path / "r.sock")
    server = _start_server(
        (sock_path, router), sock_path,
        kwargs={"pool": router,
                "extra_servers": [(tcp_srv, f"tcp:127.0.0.1:{port}")]},
    )

    requests = [
        {"id": 100 + k, "kind": "decode", "seq": "".join(BASES[syms]),
         "name": nm}
        for k, (nm, syms) in enumerate(names_syms)
    ]
    # Endpoint 0 never existed (the router front's unix door "died");
    # the TCP side door serves, then ALSO drops the connection
    # mid-stream — the client rotates through the list both times.
    endpoints = [str(tmp_path / "gone.sock"), f"tcp:127.0.0.1:{port}"]
    plan = FaultPlan([Fault("transport.read", kind="disconnect", nth=2)],
                     name="conn-death")
    with faultplan.active(plan):
        responses = serve_client.run_socket_session(
            endpoints, requests, reconnects=6, reconnect_wait_s=0.05,
        )
    assert len(plan.injected) == 1  # the mid-stream disconnect fired
    assert set(responses) == {100, 101, 102, 103}
    for k, (nm, _syms) in enumerate(names_syms):
        resp = responses[100 + k]
        assert resp["ok"], resp.get("error")
        assert resp.get("islands_text", "") == "".join(
            want_text.get(nm, [])
        ), nm

    _send_shutdown(sock_path)
    server.join(timeout=60.0)
    assert not server.is_alive()
