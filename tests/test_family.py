"""Model-family layer tests: the partition oracle, router agreement,
dense-vs-reduced parity for the new members, and the compare workload's
acceptance contract (bit-identity + zero fresh compiles)."""

import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu import family
from cpgisland_tpu.family import partition as fam
from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import HmmParams, sample_sequence
from cpgisland_tpu.utils import codec


def _members_matrix():
    """(name, params) for every preset/family shape the routers must agree
    on."""
    key = jax.random.PRNGKey(0)
    return [
        ("durbin8", presets.durbin_cpg8()),
        ("two_state", presets.two_state_cpg()),
        ("dinuc_cpg", presets.dinuc_cpg()),
        ("null4", presets.null_background(4)),
        ("null16", presets.null_background(16)),
        ("rand_g2_s4", presets.random_hmm(key, 8, 4, partition=2)),
        ("rand_g2_s8", presets.random_hmm(key, 16, 8, partition=2)),
        ("rand_g2_s16", presets.random_hmm(key, 32, 16, partition=2)),
        ("rand_g3", presets.random_hmm(key, 12, 4, partition=3)),
        ("rand_dense", presets.random_hmm(key, 8, 4)),
    ]


# ---------------------------------------------------------------------------
# partition oracle


def test_partition_flagship_structure():
    p = fam.partition_of(presets.durbin_cpg8())
    assert p is not None
    assert p.n_blocks == 4 and p.uniform == 2 and p.onehot and p.reduced
    # Group table = the reference labeling: symbol x <- states (x, x+4).
    np.testing.assert_array_equal(
        p.group_table, np.stack([np.arange(4), np.arange(4) + 4], axis=1)
    )
    assert p.entry_group(2) == (2, 6)


def test_partition_dinuc_structure():
    p = fam.partition_of(presets.dinuc_cpg())
    assert p is not None
    assert p.n_blocks == 16 and p.uniform == 2 and p.reduced
    # Pair symbol o <- states (o, o+16): the +/- pair states.
    np.testing.assert_array_equal(
        p.group_table, np.stack([np.arange(16), np.arange(16) + 16], axis=1)
    )


def test_partition_single_block_not_reduced():
    # Strictly positive emissions partition trivially into ONE block —
    # a partition, but never reduced (not one-hot).
    p = fam.partition_of(presets.two_state_cpg())
    assert p is not None and p.n_blocks == 1 and not p.onehot
    assert not p.reduced
    assert not fam.reduced_eligible(presets.two_state_cpg())


def test_partition_rejects_overlapping_supports():
    # sym0 <- {0,1}, sym1 <- {1,2}: overlapping, non-equal supports.
    B = np.array([[0.5, 0.0], [0.5, 0.5], [0.0, 0.5]])
    A = np.full((3, 3), 1.0 / 3)
    params = HmmParams.from_probs(np.full(3, 1 / 3), A, B)
    assert fam.partition_concrete(params) is False
    assert fam.partition_of(params) is None
    assert fam.reduced_eligible_concrete(params) is False


def test_partition_traced_params_undecidable():
    params = presets.durbin_cpg8()
    seen = []

    def f(log_B):
        traced = HmmParams(
            log_pi=params.log_pi, log_A=params.log_A, log_B=log_B
        )
        seen.append((
            fam.partition_concrete(traced),
            fam.reduced_eligible_concrete(traced),
            fam.reduced_eligible(traced),
        ))
        return log_B

    jax.make_jaxpr(f)(params.log_B)
    assert seen == [(None, None, False)]


def test_reduced_stats_eligibility_pow2_gate():
    key = jax.random.PRNGKey(1)
    assert fam.reduced_stats_eligible(presets.durbin_cpg8())
    assert fam.reduced_stats_eligible(presets.dinuc_cpg())
    # 2 states/symbol but a non-pow2 alphabet: reduced yes, stats no.
    odd = presets.random_hmm(key, 6, 3, partition=2)
    assert fam.reduced_eligible(odd)
    assert not fam.reduced_stats_eligible(odd)


def test_random_hmm_partition_kwarg_validates():
    key = jax.random.PRNGKey(2)
    with pytest.raises(ValueError, match="partition"):
        presets.random_hmm(key, 9, 4, partition=2)
    for g, s in ((2, 2), (2, 8), (4, 4)):
        p = presets.random_hmm(key, g * s, s, partition=g)
        p.validate()
        part = fam.partition_of(p)
        assert part is not None and part.uniform == g and part.n_blocks == s
        assert part.reduced == (g == 2)


# ---------------------------------------------------------------------------
# router agreement (the four collapsed routing sites)


def test_all_routers_agree_on_eligibility_every_preset():
    """Satellite regression: explicit-engine validation at every router
    accepts/rejects consistently with the ONE family oracle.  Since the
    K<=8 lift (ROADMAP item 2) the FB/train envelope is the REDUCED one
    (fb_onehot.ONEHOT_MAX_STATES — the 32-state dinuc member is in)."""
    from cpgisland_tpu.ops.fb_onehot import ONEHOT_MAX_STATES
    from cpgisland_tpu.parallel.decode import resolve_engine
    from cpgisland_tpu.parallel.posterior import resolve_fb_engine as post_res
    from cpgisland_tpu.train.backends import (
        _seq_onehot,
        resolve_fb_engine as train_res,
    )

    for name, params in _members_matrix():
        eligible = fam.reduced_eligible(params)
        env_ok = params.n_states <= ONEHOT_MAX_STATES

        def raises(fn) -> bool:
            try:
                fn()
                return False
            except ValueError:
                return True

        # decode: eligibility is exactly the family oracle.
        assert raises(
            lambda: resolve_engine("onehot", params)
        ) == (not eligible), name
        # posterior/train onehot additionally need the reduced state
        # envelope (boundary glue / stats accumulators scatter [K] rows).
        fb_ok = eligible and env_ok
        assert raises(
            lambda: post_res("onehot", params)
        ) == (not fb_ok), name
        assert raises(
            lambda: train_res("onehot", params, "rescaled")
        ) == (not fb_ok), name
        # the whole-sequence router's auto gate IS the family oracle
        # (inside the envelope).
        assert _seq_onehot("auto", params) == (eligible and env_ok), name


def test_auto_routing_agrees_under_tpu(monkeypatch):
    """Under a (faked) TPU backend, every 'auto' router upgrades to the
    reduced engines exactly per the family oracle (inside the reduced
    state envelope — K<=8 lifted to fb_onehot.ONEHOT_MAX_STATES)."""
    from cpgisland_tpu.ops.fb_onehot import ONEHOT_MAX_STATES
    from cpgisland_tpu.parallel import decode as dec_mod
    from cpgisland_tpu.parallel import posterior as post_mod
    from cpgisland_tpu.train import backends as train_mod

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    for name, params in _members_matrix():
        eligible = fam.reduced_eligible(params)
        env_ok = params.n_states <= ONEHOT_MAX_STATES
        d = dec_mod.resolve_engine("auto", params)
        assert (d == "onehot") == eligible, name
        p = post_mod.resolve_fb_engine("auto", params)
        assert (p == "onehot") == (eligible and env_ok), name
        t = train_mod.resolve_fb_engine("auto", params, "rescaled")
        assert (t == "onehot") == (
            fam.reduced_stats_eligible(params) and env_ok
        ), name


def test_supports_wrappers_are_family_thin():
    from cpgisland_tpu.ops import fb_onehot, viterbi_onehot

    for name, params in _members_matrix():
        assert viterbi_onehot.supports(params) == fam.reduced_eligible(
            params
        ), name
        assert fb_onehot.supports_concrete(
            params
        ) == fam.reduced_eligible_concrete(params), name


# ---------------------------------------------------------------------------
# codec pair recode


def test_recode_pairs_basic_and_prev():
    s = np.array([0, 1, 2, 3], np.uint8)
    out = codec.recode_pairs(s)
    assert out[0] == 0 * 4 + 0  # no left context -> self-context pair
    np.testing.assert_array_equal(out[1:], [0 * 4 + 1, 1 * 4 + 2, 2 * 4 + 3])
    out2 = codec.recode_pairs(s, prev=3)
    assert out2[0] == 3 * 4 + 0
    # CpG event is pair index 6.
    cg = codec.recode_pairs(np.array([1, 2], np.uint8), prev=0)
    assert cg[1] == presets.CPG_PAIR == 6


def test_recode_pairs_pad_propagation():
    s = np.array([0, 4, 2, 1], np.uint8)  # mid-stream PAD (mask policy)
    out = codec.recode_pairs(s)
    # PAD stays PAD; real positions after it get the self-context pair
    # (chain-consistent — see the recode_pairs docstring).
    np.testing.assert_array_equal(out, [0, 16, 2 * 4 + 2, 2 * 4 + 1])
    assert codec.recode_pairs(np.zeros(0, np.uint8)).size == 0
    # ...but order-2 MEMBERS reject PAD-containing base streams outright.
    with pytest.raises(ValueError, match="PAD-free"):
        family.builtin_member("dinuc_cpg").encode(s)


# ---------------------------------------------------------------------------
# dense-vs-reduced parity for the new family members (off-TPU: the reduced
# engines' XLA scan twins — the TPU kernels are certified by bench.py's
# parity phase on the capturing silicon)


def _pair_record(n, seed=0):
    rng = np.random.default_rng(seed)
    base = rng.integers(0, 4, size=n + 1).astype(np.uint8)
    return codec.recode_pairs(base[1:], prev=int(base[0]))


@pytest.mark.parametrize("member", ["dinuc", "rand_s8"])
def test_decode_parity_family_members(member):
    from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel

    if member == "dinuc":
        params, obs = presets.dinuc_cpg(), _pair_record(4096, 3)
    else:
        params = presets.random_hmm(jax.random.PRNGKey(9), 16, 8, partition=2)
        obs = np.random.default_rng(4).integers(0, 8, size=4096).astype(np.uint8)
    o = jnp.asarray(obs.astype(np.int32))
    p_x, s_x = viterbi_parallel(params, o, engine="xla")
    p_o, s_o = viterbi_parallel(params, o, engine="onehot")
    np.testing.assert_array_equal(np.asarray(p_x), np.asarray(p_o))
    assert abs(float(s_x) - float(s_o)) <= 2e-6 * max(abs(float(s_x)), 1.0)


def test_decode_batch_parity_dinuc_ragged():
    """Ragged batch geometries through the flat reset-step stream vs the
    dense vmap route — the engines' batched contract for the new member."""
    from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel_batch

    params = presets.dinuc_cpg()
    rng = np.random.default_rng(5)
    lens = np.array([1000, 777, 512, 64], np.int32)
    chunks = np.full((4, 1024), 16, np.uint8)
    for i, ln in enumerate(lens):
        chunks[i, :ln] = _pair_record(ln, seed=100 + i)
    px, sx = viterbi_parallel_batch(
        params, jnp.asarray(chunks), jnp.asarray(lens), engine="xla"
    )
    po, so = viterbi_parallel_batch(
        params, jnp.asarray(chunks), jnp.asarray(lens), engine="onehot"
    )
    for i, ln in enumerate(lens):
        np.testing.assert_array_equal(
            np.asarray(px)[i, :ln], np.asarray(po)[i, :ln], err_msg=f"rec {i}"
        )
        # Flat scores quantize at stream magnitude (documented caveat).
        assert abs(float(sx[i]) - float(so[i])) <= 1e-4 * abs(float(sx[i]))


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_posterior_and_em_parity_random_partition():
    from cpgisland_tpu.parallel.posterior import posterior_sharded
    from cpgisland_tpu.train.backends import LocalBackend

    params = presets.random_hmm(jax.random.PRNGKey(11), 8, 4, partition=2)
    obs = np.random.default_rng(6).integers(0, 4, size=8192).astype(np.uint8)
    cx, px = posterior_sharded(params, obs, (0, 1), engine="xla", want_path=True)
    co, po = posterior_sharded(
        params, obs, (0, 1), engine="onehot", want_path=True
    )
    assert float(np.abs(np.asarray(cx) - np.asarray(co)).max()) < 5e-5
    np.testing.assert_array_equal(np.asarray(px), np.asarray(po))

    chunks = jnp.asarray(obs.reshape(8, 1024))
    lens = jnp.full(8, 1024, jnp.int32)
    sx = LocalBackend(mode="rescaled", engine="xla")(params, chunks, lens)
    so = LocalBackend(mode="rescaled", engine="onehot")(params, chunks, lens)
    for nm in ("init", "trans", "emit"):
        a, b = np.asarray(getattr(sx, nm)), np.asarray(getattr(so, nm))
        assert float(np.abs(a - b).max()) < 2e-3, nm
    assert abs(float(sx.loglik) - float(so.loglik)) < 1e-2


def test_dinuc_pair_lift_equals_flagship():
    """The order-2 dinucleotide member over the pair stream is the exact
    pair-state lifting of the flagship chain: same record log-likelihood
    and the same island-confidence track (up to f32 roundoff) — the
    strongest cross-check of the whole order-2 path."""
    from cpgisland_tpu.ops.forward_backward import sequence_loglik
    from cpgisland_tpu.parallel.posterior import posterior_sharded

    _, obs = sample_sequence(
        presets.durbin_cpg8(), jax.random.PRNGKey(7), 16384
    )
    obs = np.asarray(obs)
    flag, dinuc = presets.durbin_cpg8(), presets.dinuc_cpg()
    pair = codec.recode_pairs(obs)
    ll_f = float(sequence_loglik(flag, jnp.asarray(obs.astype(np.int32))))
    ll_d = float(sequence_loglik(dinuc, jnp.asarray(pair.astype(np.int32))))
    # EXACT lift: every complete-path probability equals the flagship's
    # times the 1/4 prior split of the opening (self-context) pair state,
    # so the logliks differ by exactly -log 4 (to f32 accumulation).
    assert abs((ll_f - np.log(4.0)) - ll_d) <= 1e-4 * abs(ll_f)

    cf, _ = posterior_sharded(flag, obs, tuple(range(4)), engine="xla")
    cd, _ = posterior_sharded(dinuc, pair, tuple(range(16)), engine="xla")
    # The constant prior factor cancels in posteriors: identical tracks.
    assert float(np.abs(np.asarray(cf) - np.asarray(cd)).max()) < 1e-3


def test_sequence_loglik_matches_posterior_marginals():
    from cpgisland_tpu.ops.forward_backward import (
        posterior_marginals,
        sequence_loglik,
    )

    params = presets.two_state_cpg()
    obs = np.random.default_rng(8).integers(0, 4, size=2048).astype(np.int32)
    _, ll_ref = posterior_marginals(params, jnp.asarray(obs))
    ll = sequence_loglik(params, jnp.asarray(obs))
    assert abs(float(ll) - float(ll_ref)) < 1e-3


def test_sequence_loglik_pad_positions_unscored():
    from cpgisland_tpu.ops.forward_backward import sequence_loglik

    params = presets.durbin_cpg8()
    obs = np.random.default_rng(9).integers(0, 4, size=256).astype(np.int32)
    ll = float(sequence_loglik(params, jnp.asarray(obs)))
    # Tail PAD via symbol sentinel == tail PAD via length: identical.
    padded = np.concatenate([obs, np.full(64, 4, np.int32)])
    assert float(sequence_loglik(params, jnp.asarray(padded))) == pytest.approx(ll, abs=1e-4)
    assert float(
        sequence_loglik(params, jnp.asarray(padded), 256)
    ) == pytest.approx(ll, abs=1e-4)


# ---------------------------------------------------------------------------
# members + compare workload


def test_member_registry_and_validation():
    assert set(family.MEMBER_NAMES) == {
        "durbin8", "two_state", "dinuc_cpg", "null", "null16"
    }
    with pytest.raises(ValueError, match="unknown family member"):
        family.builtin_member("nope")
    with pytest.raises(ValueError, match="duplicate"):
        family.members_from_names(("null", "null"))
    m = family.member_from_params("x", presets.durbin_cpg8())
    assert m.island_states == (0, 1, 2, 3)
    assert family.member_from_params("y", presets.null_background(4)).island_states == ()
    with pytest.raises(ValueError, match="island states"):
        family.Member("bad", presets.two_state_cpg(), (5,))
    # Stream order is inferred from (and validated against) the alphabet:
    # a loaded pair-alphabet model must consume the pair recode, never the
    # base stream (it would nan-collapse on its structural zeros).
    m16 = family.member_from_params("d", presets.dinuc_cpg())
    assert m16.order == 2 and m16.island_states == tuple(range(16))
    with pytest.raises(ValueError, match="4-symbol"):
        family.Member("bad16", presets.dinuc_cpg(), (), order=1)
    with pytest.raises(ValueError, match="16-symbol"):
        family.Member("bad4", presets.two_state_cpg(), (0,), order=2)
    with pytest.raises(ValueError, match="infer stream order"):
        key = jax.random.PRNGKey(3)
        family.member_from_params(
            "odd", presets.random_hmm(key, 16, 8, partition=2)
        )


def test_winner_track_rejects_negative_threshold():
    with pytest.raises(ValueError, match="threshold"):
        family.winner_track(np.zeros((2, 8), np.float32), threshold=-1.0)


def test_sequence_loglik_impossible_observation_is_neg_inf():
    """A structurally impossible observation scores -inf, never nan (the
    nan would poison every member's log-odds through the baseline)."""
    from cpgisland_tpu.ops.forward_backward import sequence_loglik

    dinuc = presets.dinuc_cpg()
    # A non-chain-consistent pair stream: (a,c) followed by (g,t) — the
    # second pair's prev 'g' != the first pair's cur 'c'.
    bad = jnp.asarray(np.array([0 * 4 + 1, 2 * 4 + 3], np.int32))
    ll = float(sequence_loglik(dinuc, bad))
    assert ll == float("-inf")


def test_compare_bit_identical_to_independent_posterior_runs():
    """Acceptance: the 3-model comparison's per-member conf tracks and
    island calls are BIT-IDENTICAL to independent posterior runs of the
    same records through the shared record unit."""
    from cpgisland_tpu import pipeline
    from cpgisland_tpu import resilience
    from cpgisland_tpu.ops import islands as islands_mod
    from cpgisland_tpu.parallel.posterior import resolve_fb_engine

    _, obs = sample_sequence(
        presets.durbin_cpg8(), jax.random.PRNGKey(21), 12000
    )
    obs = np.asarray(obs)
    members = family.default_members()
    rc = family.compare_record(members, obs, record="r")

    sup = resilience.default_supervisor()
    for m in members:
        if m.is_null:
            assert not np.any(rc.member(m.name).conf)
            continue
        fb_eng = resolve_fb_engine("auto", m.params)
        conf, path = pipeline._posterior_record_unit(
            m.params, m.encode(obs), m.island_states, engine="auto",
            fb_eng=fb_eng, want_path=True, return_device=False, sup=sup,
        )
        np.testing.assert_array_equal(
            rc.member(m.name).conf, np.asarray(conf), err_msg=m.name
        )
        ref_calls = islands_mod.call_islands_obs(
            np.asarray(path), obs, island_states=m.island_states
        )
        got = rc.member(m.name).calls
        np.testing.assert_array_equal(got.beg, ref_calls.beg)
        np.testing.assert_array_equal(got.end, ref_calls.end)
        np.testing.assert_array_equal(got.gc_content, ref_calls.gc_content)

    # log-odds: baseline resolves to the null member, whose odds are 0.
    assert rc.baseline == "null"
    assert rc.member("null").log_odds == 0.0
    assert rc.member("durbin8").log_odds > 0  # data sampled from durbin8
    # winner track: every winning index names a non-null member, and the
    # winner's confidence beats the threshold at each claimed position.
    w = rc.winner
    assert w.shape == (12000,)
    for idx in np.unique(w[w >= 0]):
        assert not members[idx].is_null
    confs = np.stack([m.conf for m in rc.members])
    claimed = w >= 0
    assert np.all(
        confs[w[claimed], np.nonzero(claimed)[0]]
        > family.DEFAULT_WINNER_THRESHOLD
    )


def test_compare_zero_fresh_compiles_on_second_stream():
    from cpgisland_tpu import obs as obs_mod

    members = family.default_members()
    rng = np.random.default_rng(31)
    rec1 = rng.integers(0, 4, size=5000).astype(np.uint8)
    rec2 = rng.integers(0, 4, size=6000).astype(np.uint8)  # same pow2 bucket
    family.compare_record(members, rec1, record="warm")
    with obs_mod.no_new_compiles(tag="compare.second-stream"):
        family.compare_record(members, rec2, record="steady")


def test_compare_file_report(tmp_path):
    from cpgisland_tpu import pipeline

    _, obs = sample_sequence(
        presets.durbin_cpg8(), jax.random.PRNGKey(13), 9000
    )
    obs = np.asarray(obs)
    fa = tmp_path / "cmp.fa"
    fa.write_text(
        ">recA\n" + codec.decode_symbols(obs[:5000]) + "\n>recB\n"
        + codec.decode_symbols(obs[5000:]) + "\n"
    )
    out = io.StringIO()
    res = pipeline.compare_file(str(fa), out=out)
    assert res.n_records == 2 and res.n_symbols == 9000
    assert res.member_names == ["durbin8", "two_state", "null"]
    text = out.getvalue().splitlines()
    assert text[0].startswith("# cpgisland compare models=durbin8,")
    assert "baseline=null" in text[0]
    headers = [ln for ln in text if ln.startswith("# model ")]
    assert len(headers) == 6  # 3 members x 2 records
    assert all("log_odds" in h and "loglik" in h for h in headers)
    # Winner-track lines carry record|member name columns (multi-record).
    body = [ln for ln in text if not ln.startswith("#")]
    assert body and all(
        ln.split(" ", 1)[0].split("|")[0] in ("recA", "recB") for ln in body
    )
    names = {ln.split(" ", 1)[0].split("|")[1] for ln in body}
    assert names <= {"durbin8", "two_state"}
    # Unknown baseline rejected up front.
    with pytest.raises(ValueError, match="baseline"):
        pipeline.compare_file(str(fa), baseline="zzz")


def test_compare_includes_order2_member():
    """dinuc_cpg participates through its pair recode and (being the exact
    pair lift) matches the flagship's log-odds to f32 accumulation."""
    _, obs = sample_sequence(
        presets.durbin_cpg8(), jax.random.PRNGKey(17), 8000
    )
    obs = np.asarray(obs)
    members = family.members_from_names(("durbin8", "dinuc_cpg", "null"))
    rc = family.compare_record(members, obs)
    lo_f = rc.member("durbin8").log_odds
    lo_d = rc.member("dinuc_cpg").log_odds
    # The exact pair lift: log-odds differ by the lift's -log 4 prior
    # constant and nothing else.
    assert abs((lo_f - np.log(4.0)) - lo_d) <= 1e-3 * max(abs(lo_f), 1.0)
    # Tracks live on base coordinates: dinuc islands MATCH the flagship's
    # (identical conf tracks -> identical MPM island membership).
    f_calls = rc.member("durbin8").calls
    d_calls = rc.member("dinuc_cpg").calls
    assert len(d_calls) == len(f_calls) > 0
    np.testing.assert_array_equal(d_calls.beg, f_calls.beg)
    np.testing.assert_array_equal(d_calls.end, f_calls.end)
