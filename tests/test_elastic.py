"""Failure detection / elastic recovery (train/elastic.py + fit fallback).

The reference delegates all of this to Hadoop (task retry, skip-bad-records);
here it's first-class and testable: fault-injecting backends simulate device
failures and numerics blowups, and the recovered statistics must equal the
clean full-batch result exactly (statistics are additive, so micro-batching is
lossless).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from cpgisland_tpu.models import presets
from cpgisland_tpu.ops.forward_backward import SuffStats
from cpgisland_tpu.train import baum_welch
from cpgisland_tpu.train.backends import EStepBackend, LocalBackend
from cpgisland_tpu.train.elastic import ElasticEStep
from cpgisland_tpu.utils import chunking


@pytest.fixture
def data(rng):
    syms = rng.integers(0, 4, size=16 * 256).astype(np.uint8)
    return chunking.frame(syms, 256)


class FlakyBackend(EStepBackend):
    """Delegates to LocalBackend but raises on the first ``n_failures`` calls."""

    def __init__(self, n_failures, exc=RuntimeError("injected device fault")):
        self.inner = LocalBackend(mode="rescaled", engine="xla")
        self.remaining = n_failures
        self.exc = exc
        self.calls = 0

    def __call__(self, params, chunks, lengths):
        self.calls += 1
        if self.remaining > 0:
            self.remaining -= 1
            raise self.exc
        return self.inner(params, chunks, lengths)


class NaNBackend(EStepBackend):
    """Returns NaN-poisoned statistics on the first ``n_bad`` calls."""

    def __init__(self, n_bad):
        self.inner = LocalBackend(mode="rescaled", engine="xla")
        self.remaining = n_bad

    def __call__(self, params, chunks, lengths):
        stats = self.inner(params, chunks, lengths)
        if self.remaining > 0:
            self.remaining -= 1
            return SuffStats(
                init=stats.init, trans=stats.trans * jnp.nan, emit=stats.emit,
                loglik=stats.loglik, n_seqs=stats.n_seqs,
            )
        return stats


def _clean_stats(params, data):
    b = LocalBackend(mode="rescaled", engine="xla")
    return b(params, jnp.asarray(data.chunks), jnp.asarray(data.lengths))


def assert_stats_close(a, b, tol=1e-5):
    np.testing.assert_allclose(np.asarray(a.trans), np.asarray(b.trans), rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(a.emit), np.asarray(b.emit), rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(a.init), np.asarray(b.init), rtol=tol, atol=tol)
    assert float(a.loglik) == pytest.approx(float(b.loglik), abs=0.01)


def test_micro_batched_sum_equals_full_batch(data):
    params = presets.durbin_cpg8()
    el = ElasticEStep(LocalBackend(mode="rescaled", engine="xla"), micro_batches=4)
    got = el(params, data.chunks, data.lengths)
    assert_stats_close(got, _clean_stats(params, data))
    assert el.failures == []


def test_retry_recovers_from_transient_faults(data):
    params = presets.durbin_cpg8()
    flaky = FlakyBackend(n_failures=2)
    el = ElasticEStep(flaky, micro_batches=4, max_retries=2)
    got = el(params, data.chunks, data.lengths)
    assert_stats_close(got, _clean_stats(params, data))
    assert el.failures == []
    assert flaky.calls > 4  # retries actually happened


def test_nan_stats_detected_and_retried(data):
    params = presets.durbin_cpg8()
    el = ElasticEStep(NaNBackend(n_bad=1), micro_batches=4, max_retries=1)
    got = el(params, data.chunks, data.lengths)
    assert_stats_close(got, _clean_stats(params, data))


def test_persistent_failure_raises_by_default(data):
    params = presets.durbin_cpg8()
    el = ElasticEStep(FlakyBackend(n_failures=100), micro_batches=4, max_retries=1)
    with pytest.raises(RuntimeError, match="failed"):
        el(params, data.chunks, data.lengths)
    assert len(el.failures) == 1


def test_skip_mode_drops_bad_slice_and_continues(data):
    params = presets.durbin_cpg8()

    class FailsOnce(EStepBackend):
        """Fails every attempt of exactly one slice (the first one called)."""

        def __init__(self):
            self.inner = LocalBackend(mode="rescaled", engine="xla")
            self.poisoned = None

        def __call__(self, params, chunks, lengths):
            key = int(np.asarray(chunks[0, :8]).sum())
            if self.poisoned is None:
                self.poisoned = key
            if key == self.poisoned:
                raise RuntimeError("bad shard")
            return self.inner(params, chunks, lengths)

    el = ElasticEStep(FailsOnce(), micro_batches=4, max_retries=0, on_failure="skip")
    got = el(params, data.chunks, data.lengths)
    assert len(el.failures) == 1
    # surviving slices only: 12 of 16 chunks
    micro = 4
    keep = np.ones(16, bool)
    keep[el.failures[0].start : el.failures[0].stop] = False
    sub = chunking.Chunked(data.chunks[keep], data.lengths[keep], total=int(data.lengths[keep].sum()))
    assert_stats_close(got, _clean_stats(params, sub))


def test_skip_mode_blacklists_across_iterations(data):
    """A permanently-bad slice is attempted once (with retries) and then
    blacklisted — later EM iterations don't waste re-attempts on it."""
    params = presets.durbin_cpg8()

    class CountingPoison(EStepBackend):
        def __init__(self):
            self.inner = LocalBackend(mode="rescaled", engine="xla")
            self.poisoned = None
            self.poison_calls = 0

        def __call__(self, params, chunks, lengths):
            key = int(np.asarray(chunks[0, :8]).sum())
            if self.poisoned is None:
                self.poisoned = key
            if key == self.poisoned:
                self.poison_calls += 1
                raise RuntimeError("bad shard")
            return self.inner(params, chunks, lengths)

    poison = CountingPoison()
    el = ElasticEStep(poison, micro_batches=4, max_retries=1, on_failure="skip")
    el(params, data.chunks, data.lengths)
    el(params, data.chunks, data.lengths)
    el(params, data.chunks, data.lengths)
    assert poison.poison_calls == 2  # retries of call 1 only; then blacklisted
    assert len(el.failures) == 1


def test_fit_does_not_recover_programming_errors(data):
    """ValueError from a misconfigured backend surfaces immediately (no
    retry, no fallback reroute)."""
    params = presets.durbin_cpg8()

    class Misconfigured(EStepBackend):
        def __call__(self, params, chunks, lengths):
            raise ValueError("wrong input layout")

    with pytest.raises(ValueError, match="wrong input layout"):
        baum_welch.fit(
            params, data, num_iters=2, convergence=0.0,
            backend=Misconfigured(), fallback_backend=LocalBackend(),
        )


def test_fit_switches_to_fallback_backend(data):
    params = presets.durbin_cpg8()
    bad = NaNBackend(n_bad=100)  # never recovers on its own
    res = baum_welch.fit(
        params, data, num_iters=3, convergence=0.0,
        backend=bad, fallback_backend=LocalBackend(mode="log", engine="xla"),
    )
    assert res.iterations == 3
    assert len(res.recoveries) == 1 and res.recoveries[0][0] == 1
    assert all(np.isfinite(res.logliks))
    clean = baum_welch.fit(
        params, data, num_iters=3, convergence=0.0,
        backend=LocalBackend(mode="log", engine="xla"),
    )
    np.testing.assert_allclose(np.asarray(res.params.A), np.asarray(clean.params.A), atol=1e-5)


def test_fit_raises_without_fallback(data):
    params = presets.durbin_cpg8()
    with pytest.raises(FloatingPointError):
        baum_welch.fit(params, data, num_iters=2, convergence=0.0, backend=NaNBackend(100))


def test_fit_transient_fault_single_retry(data):
    params = presets.durbin_cpg8()
    flaky = FlakyBackend(n_failures=1)
    res = baum_welch.fit(params, data, num_iters=2, convergence=0.0, backend=flaky)
    assert res.iterations == 2
    assert res.recoveries == []  # same-backend retry is not a backend switch
