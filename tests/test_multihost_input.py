"""Multi-host input sharding: the HDFS-input-split equivalent.

Single-process tests of the shard-selection math (utils.chunking.process_shard)
and of SpmdBackend.place's multi-host branch (monkeypatched process topology —
a real pod isn't available in CI, but the contract each host must satisfy is
fully checkable: contiguous disjoint cover, alignment with the data-axis
device order, and statistics that sum to the global answer).
Reference: CpGIslandFinder.java:108-147 (HDFS SequenceFile input splits).
"""

import jax
import numpy as np
import pytest

from conftest import require_devices

from cpgisland_tpu.models import presets
from cpgisland_tpu.train import backends
from cpgisland_tpu.utils import chunking


def _chunked(rng, n_chunks, size=64):
    syms = rng.integers(0, 4, size=n_chunks * size - 17).astype(np.uint8)
    return chunking.frame(syms, size)


def test_process_shard_disjoint_cover(rng):
    ck = _chunked(rng, 10)
    P = 4
    shards = [chunking.process_shard(ck, p, P) for p in range(P)]
    padded = chunking.pad_to_multiple(ck, P)
    # equal-size contiguous blocks, in order, covering every padded row once
    n_local = padded.num_chunks // P
    assert all(s.num_chunks == n_local for s in shards)
    rebuilt = np.concatenate([s.chunks for s in shards])
    np.testing.assert_array_equal(rebuilt, padded.chunks)
    # local totals sum to the global symbol count
    assert sum(s.total for s in shards) == ck.total


def test_process_shard_validation(rng):
    ck = _chunked(rng, 4)
    with pytest.raises(ValueError):
        chunking.process_shard(ck, 4, 4)
    with pytest.raises(ValueError):
        chunking.process_shard(ck, -1, 4)


def test_process_shard_stats_sum_to_global(rng):
    """Per-process local E-steps summed == the undivided global E-step —
    the invariant that makes each host feeding only its shard correct."""
    params = presets.durbin_cpg8()
    ck = _chunked(rng, 6, size=96)
    local = backends.LocalBackend(engine="xla")
    want = local(params, ck.chunks, ck.lengths)
    P = 3
    parts = [
        local(params, s.chunks, s.lengths)
        for s in (chunking.process_shard(ck, p, P) for p in range(P))
    ]
    got = parts[0]
    for p in parts[1:]:
        got = got + p
    np.testing.assert_allclose(np.asarray(got.trans), np.asarray(want.trans), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(got.emit), np.asarray(want.emit), rtol=1e-5)
    np.testing.assert_allclose(float(got.loglik), float(want.loglik), rtol=1e-6)
    assert int(got.n_seqs) == int(want.n_seqs)


def test_spmd_place_multihost_branch(rng, monkeypatch):
    """With a faked 2-process topology, place() must hand
    make_array_from_process_local_data exactly this process's contiguous
    block and the global shape."""
    require_devices(8)
    from cpgisland_tpu.parallel.mesh import make_mesh

    backend = backends.SpmdBackend(mesh=make_mesh(8, axis="data"))
    ck = backend.prepare(_chunked(rng, 16, size=32))
    calls = []

    def fake_make_array(sharding, local, global_shape):
        calls.append((np.asarray(local), tuple(global_shape)))
        import jax.numpy as jnp

        return jax.device_put(jnp.zeros(global_shape, local.dtype), sharding)

    monkeypatch.setattr(backends.jax, "process_count", lambda: 2)
    monkeypatch.setattr(backends.jax, "process_index", lambda: 1)
    monkeypatch.setattr(
        backends.jax, "make_array_from_process_local_data", fake_make_array
    )
    backend.place(ck.chunks, ck.lengths)
    (loc_chunks, gshape_c), (loc_lens, gshape_l) = calls
    assert gshape_c == ck.chunks.shape and gshape_l == ck.lengths.shape
    n_local = ck.num_chunks // 2
    np.testing.assert_array_equal(loc_chunks, ck.chunks[n_local:])
    np.testing.assert_array_equal(loc_lens, ck.lengths[n_local:])


def test_spmd_place_single_process_unchanged(rng):
    """process_count()==1 keeps the plain device_put path and fit() runs."""
    require_devices(8)
    from cpgisland_tpu.parallel.mesh import make_mesh
    from cpgisland_tpu.train import baum_welch

    backend = backends.SpmdBackend(mesh=make_mesh(8, axis="data"))
    ck = _chunked(rng, 16, size=32)
    res = baum_welch.fit(
        presets.durbin_cpg8(), ck, num_iters=1, convergence=0.0, backend=backend
    )
    assert np.isfinite(res.logliks[0])


def test_distributed_chunked_single_process_parity(tmp_path, rng):
    """distributed_chunked == frame + pad_to_multiple when P == 1."""
    from cpgisland_tpu.utils import chunking, codec

    fa = tmp_path / "g.fa"
    with open(fa, "w") as f:
        f.write(">r\n")
        s = "".join(rng.choice(list("acgt"), size=30_000))
        for i in range(0, len(s), 70):
            f.write(s[i : i + 70] + "\n")
    whole = codec.encode_file(str(fa), skip_headers=True)
    ls = chunking.distributed_chunked(
        str(fa), 4096, pad_multiple=8, process_index=0, process_count=1
    )
    ref = chunking.pad_to_multiple(chunking.frame(whole, 4096), 8)
    np.testing.assert_array_equal(ls.chunks, ref.chunks)
    np.testing.assert_array_equal(ls.lengths, ref.lengths)
    assert ls.global_rows == ref.num_chunks
    # With a symbol cache: identical shard, sidecar created, hit served.
    cache = str(tmp_path / "c")
    for _ in range(2):
        ls_c = chunking.distributed_chunked(
            str(fa), 4096, pad_multiple=8, process_index=0, process_count=1,
            symbol_cache=cache,
        )
        np.testing.assert_array_equal(ls_c.chunks, ref.chunks)
    import os

    assert os.path.exists(f"{cache}.range0of1.npz")


def test_train_file_single_process_keeps_whole_file_parse(tmp_path, rng, monkeypatch):
    """The byte-range-sharded input path activates ONLY in multi-process
    jobs: a single-process spmd train_file still encodes the whole file
    (the shard path would be pure overhead at P=1)."""
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.utils import chunking, codec

    fa = tmp_path / "g.fa"
    with open(fa, "w") as f:
        f.write(">r\n")
        s = "".join(rng.choice(list("acgt"), size=10_000))
        for i in range(0, len(s), 70):
            f.write(s[i : i + 70] + "\n")
    called = {"dc": 0}
    orig = chunking.distributed_chunked

    def spy(*a, **kw):
        called["dc"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(chunking, "distributed_chunked", spy)
    res = pipeline.train_file(
        str(fa), compat=False, backend="spmd", num_iters=1, convergence=0.0,
        chunk_size=1024,
    )
    assert called["dc"] == 0
    assert np.isfinite(res.logliks[0])


def test_distributed_chunked_multi_part_assembly(tmp_path, rng):
    """Simulated P-process assembly (injected gather): the per-process blocks
    concatenate to EXACTLY the global framing, for part counts that force
    boundary spills in both directions."""
    from cpgisland_tpu.utils import chunking, codec

    fa = tmp_path / "g.fa"
    with open(fa, "w") as f:
        for name, nlen in (("a", 20_000), ("b", 7_000), ("c", 15_000)):
            f.write(f">{name} desc\n")
            s = "".join(rng.choice(list("acgtN"), size=nlen))
            for i in range(0, len(s), 61):
                f.write(s[i : i + 61] + "\n")
    whole = codec.encode_file(str(fa), skip_headers=True)
    C = 1024
    for P in (2, 3, 5):
        parts = [
            codec.encode_byte_range(str(fa), q, P) for q in range(P)
        ]
        counts = np.asarray([p.size for p in parts], np.int64)
        N = -(-whole.size // C)
        gr = -(-N // (2 * P)) * (2 * P)
        n_local = gr // P
        width = max(
            max(h1 - h0, t1 - t0)
            for q in range(P)
            for (h0, h1), (t0, t1) in [
                chunking._spill_ranges(q, counts, n_local, C)
            ]
        )
        spills = (
            np.stack(
                [
                    chunking._spill_buffer(parts[q], q, counts, n_local, C, width)
                    for q in range(P)
                ]
            )
            if width
            else np.zeros((P, 2, 0), np.uint8)
        )
        blocks = []
        for p in range(P):
            calls = iter([counts.reshape(P, 1), spills])
            blocks.append(
                chunking.distributed_chunked(
                    str(fa), C, pad_multiple=2 * P, process_index=p,
                    process_count=P, gather=lambda x, it=calls: next(it),
                )
            )
        ref = chunking.pad_to_multiple(chunking.frame(whole, C), 2 * P)
        np.testing.assert_array_equal(
            np.concatenate([b.chunks for b in blocks]), ref.chunks
        )
        np.testing.assert_array_equal(
            np.concatenate([b.lengths for b in blocks]), ref.lengths
        )
        assert all(b.global_rows == ref.num_chunks for b in blocks)


def test_spmd_backend_local_shard_single_process(tmp_path, rng):
    """fit() through SpmdBackend on a LocalShard (P=1 degenerate) matches
    fit() on the equivalent globally-framed batch."""
    import jax

    from conftest import require_devices
    from cpgisland_tpu.models import presets
    from cpgisland_tpu.parallel.mesh import make_mesh
    from cpgisland_tpu.train import backends, baum_welch
    from cpgisland_tpu.utils import chunking, codec

    require_devices(8)
    fa = tmp_path / "g.fa"
    with open(fa, "w") as f:
        f.write(">r\n")
        s = "".join(rng.choice(list("acgt"), size=16 * 256))
        for i in range(0, len(s), 70):
            f.write(s[i : i + 70] + "\n")
    shard = chunking.distributed_chunked(
        str(fa), 256, pad_multiple=8, process_index=0, process_count=1
    )
    r_shard = baum_welch.fit(
        presets.durbin_cpg8(), shard, num_iters=2, convergence=0.0,
        backend=backends.SpmdBackend(mesh=make_mesh(8, axis="data")),
    )
    whole = codec.encode_file(str(fa), skip_headers=True)
    r_ref = baum_welch.fit(
        presets.durbin_cpg8(), chunking.frame(whole, 256), num_iters=2,
        convergence=0.0,
        backend=backends.SpmdBackend(mesh=make_mesh(8, axis="data")),
    )
    np.testing.assert_allclose(r_shard.logliks, r_ref.logliks, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(r_shard.params.A), np.asarray(r_ref.params.A), rtol=1e-6
    )
