"""The r9 pass-count collapse: co-scheduled fwd/bwd vs the 3-pass twins.

The fused pass (fb_onehot._oh_fwdbwd_kernel / its one-scan XLA twin) runs
both probability-space chains in ONE launch with a SELF-NORMALIZED
backward; every consumer is scale-free in the betas, so results must match
the split (r4) pass structure at f32-rounding tolerance — posterior conf,
whole-sequence stats, chunked stats (z-normalized vs cs-scaled schemes),
MPM paths, span-threaded continuations.  Also covered: the flat batched
decode's EXACT per-record scores (the r9 satellite that retires the vmap
route for return_score=True) and a bounded flat-batch geometry fuzz.

Off-TPU these run the XLA twins; the TPU suite run (CPGISLAND_TEST_PLATFORM
=axon) exercises the Pallas kernels against the same assertions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import sample_sequence
from cpgisland_tpu.ops import fb_pallas
from cpgisland_tpu.ops import viterbi_onehot as OH
from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel, viterbi_parallel_batch

MASK8 = jnp.asarray(np.r_[np.ones(4), np.zeros(4)].astype(np.float32))


def _onehot_model(rng, S=4):
    """Tie-free random one-hot-emission model (the test_viterbi_onehot
    construction): iid logit perturbation makes argmax ties probability-0,
    so flat-vs-vmap path equality is exact."""
    from cpgisland_tpu.models.hmm import HmmParams

    K = 2 * S
    perm = rng.permutation(K)
    sym_of_state = np.empty(K, dtype=np.int64)
    for s in range(S):
        sym_of_state[perm[2 * s]] = s
        sym_of_state[perm[2 * s + 1]] = s
    pi = rng.dirichlet(np.ones(K))
    A = rng.dirichlet(np.ones(K), size=K)
    B = np.zeros((K, S))
    B[np.arange(K), sym_of_state] = 1.0
    A = A * np.exp(rng.normal(scale=1e-3, size=A.shape))
    A = A / A.sum(axis=1, keepdims=True)
    return HmmParams.from_probs(pi, A, B)


def _obs(rng, n):
    params = presets.durbin_cpg8()
    _, obs = sample_sequence(
        params, jax.random.PRNGKey(int(rng.integers(1 << 30))), n
    )
    return params, obs


def _f64_path_score(params, obs, path):
    """Achieved score of a state path in f64 — the engine tie contract's
    arbiter (PARITY.md C10): routes may argmax-tie differently at f32
    rounding; both choices must then be true argmaxes."""
    lp = np.asarray(params.log_pi, np.float64)
    lA = np.asarray(params.log_A, np.float64)
    lB = np.asarray(params.log_B, np.float64)
    S = lB.shape[1]
    s = lp[path[0]] + (lB[path[0], obs[0]] if obs[0] < S else 0.0)
    for t in range(1, len(obs)):
        if obs[t] >= S:
            continue
        s += lA[path[t - 1], path[t]] + lB[path[t], obs[t]]
    return s


def _assert_paths_equivalent(params, masked_obs, got, want, ctx):
    """Exact path equality, or — at an f32 rounding tie — identical f64
    achieved scores (the pinned flat-stream tie contract: the reset folds
    the previous record's constant into later additions)."""
    if np.array_equal(got, want):
        return
    sa = _f64_path_score(params, masked_obs, got)
    sb = _f64_path_score(params, masked_obs, want)
    assert sa == pytest.approx(sb, rel=1e-12), (ctx, sa, sb)


# --- posterior: fused vs split vs dense -------------------------------------


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_posterior_conf_fused_vs_split(rng):
    params, obs = _obs(rng, 30000)
    kw = dict(lane_T=4096, t_tile=512, onehot=True)
    c_split, _ = fb_pallas.seq_posterior_pallas(
        params, obs, obs.shape[0], MASK8, fused=False, **kw
    )
    c_fused, _ = fb_pallas.seq_posterior_pallas(
        params, obs, obs.shape[0], MASK8, fused=True, **kw
    )
    c_dense, _ = fb_pallas.seq_posterior_pallas(
        params, obs, obs.shape[0], MASK8, lane_T=4096, t_tile=512
    )
    np.testing.assert_allclose(np.asarray(c_fused), np.asarray(c_split), atol=2e-5)
    np.testing.assert_allclose(np.asarray(c_fused), np.asarray(c_dense), atol=2e-5)


def test_posterior_want_path_fused(rng):
    params, obs = _obs(rng, 20000)
    kw = dict(lane_T=4096, t_tile=512, onehot=True, want_path=True)
    c_s, p_s = fb_pallas.seq_posterior_pallas(
        params, obs, obs.shape[0], MASK8, fused=False, **kw
    )
    c_f, p_f = fb_pallas.seq_posterior_pallas(
        params, obs, obs.shape[0], MASK8, fused=True, **kw
    )
    np.testing.assert_allclose(np.asarray(c_f), np.asarray(c_s), atol=2e-5)
    assert np.array_equal(np.asarray(p_f), np.asarray(p_s))


def test_posterior_continuation_span_fused(rng):
    """Span-threaded continuation (enter/exit dirs + prev_sym) through the
    fused pass matches the split pass — the pipeline.posterior_file span
    contract is normalization-scheme-independent."""
    params, obs = _obs(rng, 24000)
    span = 12000
    piece = obs[span:]
    enter = np.abs(np.random.default_rng(1).normal(size=8)).astype(np.float32)
    enter /= enter.sum()
    kw = dict(
        enter_dir=jnp.asarray(enter), exit_dir=None, first=False,
        lane_T=4096, t_tile=512, onehot=True,
        prev_sym=jnp.int32(int(obs[span - 1])),
    )
    c_s, _ = fb_pallas.seq_posterior_pallas(
        params, piece, piece.shape[0], MASK8, fused=False, **kw
    )
    c_f, _ = fb_pallas.seq_posterior_pallas(
        params, piece, piece.shape[0], MASK8, fused=True, **kw
    )
    np.testing.assert_allclose(np.asarray(c_f), np.asarray(c_s), atol=2e-5)


# --- EM: fused vs split, both layouts ---------------------------------------


def _assert_stats_close(a, b, rtol=5e-5, atol=1e-3):
    np.testing.assert_allclose(np.asarray(a.init), np.asarray(b.init), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(a.trans), np.asarray(b.trans), rtol=rtol, atol=atol
    )
    np.testing.assert_allclose(
        np.asarray(a.emit), np.asarray(b.emit), rtol=rtol, atol=atol
    )
    assert float(a.loglik) == pytest.approx(float(b.loglik), rel=1e-5)
    assert int(a.n_seqs) == int(b.n_seqs)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_seq_stats_fused_vs_split(rng):
    params, obs = _obs(rng, 40000)
    s_split = fb_pallas.seq_stats_pallas(
        params, obs, obs.shape[0], lane_T=4096, onehot=True, fused=False
    )
    s_fused = fb_pallas.seq_stats_pallas(
        params, obs, obs.shape[0], lane_T=4096, onehot=True, fused=True
    )
    s_dense = fb_pallas.seq_stats_pallas(params, obs, obs.shape[0], lane_T=4096)
    _assert_stats_close(s_fused, s_split)
    _assert_stats_close(s_fused, s_dense)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_chunked_stats_fused_vs_split(rng):
    """Chunked E-step: the fused single-drain pass + z-normalized stats vs
    the split fwd/bwd + cs-scaled stats kernel vs the dense engine — all
    one scheme's f32 rounding apart (ragged lengths, empty records)."""
    params = presets.durbin_cpg8()
    N, T = 5, 3000
    chunks = np.zeros((N, T), np.uint8)
    lengths = np.asarray([3000, 2500, 1, 0, 3000], np.int32)
    for i in range(N):
        if lengths[i]:
            _, o = sample_sequence(params, jax.random.PRNGKey(i), int(lengths[i]))
            chunks[i, : lengths[i]] = np.asarray(o)
    args = (params, jnp.asarray(chunks), jnp.asarray(lengths))
    s_split = fb_pallas.batch_stats_pallas(*args, t_tile=512, onehot=True, fused=False)
    s_fused = fb_pallas.batch_stats_pallas(*args, t_tile=512, onehot=True, fused=True)
    s_dense = fb_pallas.batch_stats_pallas(*args, t_tile=512)
    _assert_stats_close(s_fused, s_split)
    _assert_stats_close(s_fused, s_dense)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_batch_posterior_fused(rng):
    params = presets.durbin_cpg8()
    N, T = 4, 2000
    chunks = np.zeros((N, T), np.uint8)
    lengths = np.asarray([2000, 1500, 1, 2000], np.int32)
    for i in range(N):
        _, o = sample_sequence(params, jax.random.PRNGKey(10 + i), int(lengths[i]))
        chunks[i, : lengths[i]] = np.asarray(o)
    for want_path in (False, True):
        c_s, p_s = fb_pallas.batch_posterior_pallas(
            params, jnp.asarray(chunks), jnp.asarray(lengths), MASK8,
            want_path=want_path, onehot=True, fused=False,
        )
        c_f, p_f = fb_pallas.batch_posterior_pallas(
            params, jnp.asarray(chunks), jnp.asarray(lengths), MASK8,
            want_path=want_path, onehot=True, fused=True,
        )
        for i in range(N):
            L = int(lengths[i])
            np.testing.assert_allclose(
                np.asarray(c_s)[i, :L], np.asarray(c_f)[i, :L], atol=2e-5
            )
            if want_path:
                assert np.array_equal(
                    np.asarray(p_s)[i, :L], np.asarray(p_f)[i, :L]
                )


def test_fused_em_fit_parity(rng):
    """End-to-end: a fused-loop Baum-Welch fit through the co-scheduled
    chunked pass reproduces the split pass's trajectory (the training-path
    acceptance for the pass collapse)."""
    from cpgisland_tpu.train import baum_welch
    from cpgisland_tpu.train.backends import LocalBackend
    from cpgisland_tpu.utils import chunking

    params, obs = _obs(rng, 16 * 1024)
    chunked = chunking.frame(np.asarray(obs).astype(np.uint8), 1024)
    res = {}
    for fuse_fb in (False, True):
        backend = LocalBackend(engine="onehot", fuse_fb=fuse_fb)
        res[fuse_fb] = baum_welch.fit(
            params, chunked, num_iters=3, convergence=0.0, backend=backend
        )
    np.testing.assert_allclose(
        np.asarray(res[True].logliks), np.asarray(res[False].logliks),
        rtol=1e-5,
    )


# --- flat batched decode: exact per-record scores ---------------------------


@pytest.mark.parametrize("seed", [0, 1])
def test_batch_flat_scores_parity(rng, seed):
    """Flat-stream per-record scores vs the vmap route AND the per-record
    decoder at ragged geometries.  Tolerance: the engines' normalizer
    offsets accumulate stream-magnitude f32 sums, so scores carry
    ulp(|chain|)-scale absolute rounding (shared with the vmap route)."""
    r = np.random.default_rng(100 + seed)
    params = _onehot_model(r)
    N, T = 5, 700
    chunks = r.integers(0, 4, size=(N, T)).astype(np.int32)
    chunks[2, 300:320] = 7  # mid-record PAD run
    lengths = np.asarray([700, 650, 700, 2, 700], np.int32)
    p_flat, s_flat = viterbi_parallel_batch(
        params, jnp.asarray(chunks), jnp.asarray(lengths), block_size=128,
        return_score=True, engine="onehot",
    )
    p_vmap, s_vmap = viterbi_parallel_batch(
        params, jnp.asarray(chunks), jnp.asarray(lengths), block_size=128,
        return_score=True, engine="onehot", vmap_records=True,
    )
    tol = 1e-3 * max(N * T, 1)  # ulp-class bound at chain magnitude
    for i in range(N):
        L = int(lengths[i])
        o = np.where(np.arange(T) >= L, 4, chunks[i])
        _assert_paths_equivalent(
            params, o, np.asarray(p_flat)[i, :L], np.asarray(p_vmap)[i, :L],
            ("flat-vs-vmap", seed, i),
        )
        _, s_ref = viterbi_parallel(
            params, jnp.asarray(o), block_size=128, return_score=True,
            engine="onehot",
        )
        assert abs(float(s_flat[i]) - float(s_ref)) <= tol, (
            i, float(s_flat[i]), float(s_ref)
        )
    np.testing.assert_allclose(
        np.asarray(s_flat), np.asarray(s_vmap), atol=tol
    )


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_batch_flat_score_arm_paths_identical(rng):
    """The score arm must not perturb the decoded paths (same passes, the
    dmax emission hangs off the recursion)."""
    params = _onehot_model(np.random.default_rng(7))
    N, T = 4, 520
    chunks = np.random.default_rng(8).integers(0, 4, size=(N, T)).astype(np.int32)
    lengths = np.asarray([520, 300, 2, 520], np.int32)
    p_only = OH.decode_batch_flat(
        params, jnp.asarray(chunks), jnp.asarray(lengths), block_size=128
    )
    p_sc, _ = OH.decode_batch_flat(
        params, jnp.asarray(chunks), jnp.asarray(lengths), block_size=128,
        return_score=True,
    )
    assert np.array_equal(np.asarray(p_only), np.asarray(p_sc))


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_batch_flat_geometry_fuzz(rng):
    """Bounded flat-batch geometry fuzz (sizes small enough for the TPU
    suite run — r5's edge coverage must not stay CPU-only): random N/T/
    block_size/ragged lengths, paths vs the per-record decoder and scores
    vs the per-record chain, per seed."""
    for seed in range(4):
        r = np.random.default_rng(1000 + seed)
        params = _onehot_model(r)
        N = int(r.integers(2, 6))
        T = int(r.integers(2, 400))
        bk = int(r.choice([8, 64, 128, 256]))
        chunks = r.integers(0, 4, size=(N, T)).astype(np.int32)
        lengths = r.integers(1, T + 1, size=N).astype(np.int32)
        p_flat, s_flat = OH.decode_batch_flat(
            params, jnp.asarray(chunks), jnp.asarray(lengths), block_size=bk,
            return_score=True,
        )
        tol = 1e-3 * max(N * T, 64)
        for i in range(N):
            L = int(lengths[i])
            o = np.where(np.arange(T) >= L, 4, chunks[i])
            ref_p, ref_s = viterbi_parallel(
                params, jnp.asarray(o), block_size=bk, return_score=True,
                engine="onehot",
            )
            _assert_paths_equivalent(
                params, o, np.asarray(p_flat)[i, :L], np.asarray(ref_p)[:L],
                (seed, i, N, T, bk),
            )
            assert abs(float(s_flat[i]) - float(ref_s)) <= tol, (
                seed, i, N, T, bk, float(s_flat[i]), float(ref_s)
            )


# --- span decode with the deferred path drain -------------------------------


def test_span_decode_deferred_drain_identical(rng):
    """viterbi_sharded_spans' r9 deferred path drain (next span dispatched
    before the previous span's path downloads) is bit-identical to the
    one-shot decode."""
    from cpgisland_tpu.parallel import decode as pdec

    params = _onehot_model(np.random.default_rng(3))
    T = 8 * 64 * 4 + 9
    obs = np.random.default_rng(4).integers(0, 4, size=T).astype(np.uint8)
    one = pdec.viterbi_sharded(params, obs, block_size=64, engine="onehot")
    spans = pdec.viterbi_sharded_spans(
        params, obs, span=8 * 64 * 2, block_size=64, engine="onehot"
    )
    assert np.array_equal(
        np.asarray(one), np.concatenate([np.asarray(p) for p in spans])
    )
