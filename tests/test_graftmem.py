"""graftcheck Layer 5 (graftmem): the static memory model and contracts.

Covers the four satellite obligations of the memory layer: (1) the
empirically-discovered hard caps are reconciled with the model — each
predicted limit must BRACKET its measured counterpart, and where the
measured cap is a perf knee rather than a memory cliff the discrepancy
is a pinned note, not a silent pass; (2) the routing sites that used to
hard-code those caps (pick_lane_T's 65536 filter, SEQ_SHARD_BUDGET)
now consult memmodel and derive bit-for-bit the shipped behavior; (3)
oversized inputs fail with the model's actionable numbers (mem_reject
events); (4) MEMORY.json lockfile mechanics (tolerance boundaries,
stale entries, the --update-mem round trip) and feasible() agreeing
with the contract verdicts across a knob grid.
"""

import json
import os
import subprocess
import sys

import jax
import pytest

from cpgisland_tpu import obs
from cpgisland_tpu.analysis import mem_contracts, memmodel

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "cpgisland_tpu.analysis", *args],
        capture_output=True, text=True, cwd=REPO,
    )


# -- the closed-form model ---------------------------------------------------


def test_buffer_cost_factors():
    b = memmodel.Buffer("x", (8, 128))
    assert b.nbytes == 8 * 128 * 4
    assert b.cost == b.nbytes                       # input stream: x1
    out = memmodel.Buffer("y", (8, 128), kind="out")
    assert out.cost == out.nbytes * memmodel.DOUBLE  # result: buffered
    for kind in ("resident", "scratch"):
        assert memmodel.Buffer("z", (8, 128), kind=kind).cost == b.nbytes


def test_kernel_registry_builds_everywhere():
    for name in memmodel.kernels():
        fp = memmodel.footprint(name)
        assert fp.total > 0, name
        assert fp.buffers, name
    with pytest.raises(KeyError, match="unknown kernel"):
        memmodel.footprint("decode.nope")


def test_feasible_agrees_with_footprint_across_knob_grid():
    """feasible() and the raw footprint-vs-limit comparison must agree at
    every grid point — the autotuner prunes on the former, the contract
    reasons with the latter."""
    limit = memmodel.vmem_limit()
    grid = []
    for bk in (256, 1024, 4096, 8192, 16384):
        for m in (1, 2, 3):
            grid.append(memmodel.Knobs(block_size=bk, stacked_m=m))
    for lane_T in (8192, 65536, 131072):
        for lt in (128, 256):
            grid.append(memmodel.Knobs(lane_T=lane_T, lane_tile=lt))
    for kernel in memmodel.kernels():
        for knobs in grid:
            f = memmodel.feasible(kernel, knobs)
            assert f.ok == (
                memmodel.footprint(kernel, knobs).total <= limit
            ), (kernel, knobs)
            if not f.ok:
                assert f.offenders and f.reason, (kernel, knobs)


def test_shipped_knobs_all_fit_and_contract_agrees():
    contract = mem_contracts._vmem_budget_contract()
    assert contract.ok, contract.violations
    for name, knobs in mem_contracts.shipped_knobs().items():
        assert memmodel.feasible(
            mem_contracts._kernel_for(name), knobs
        ).ok, name


# -- routing parity: derived caps == shipped behavior, bit for bit -----------


def test_pick_lane_T_candidate_parity_with_legacy_filter():
    """The memmodel-filtered candidate sets must equal the hard-coded
    sets pick_lane_T shipped before graftmem: dense = the whole rate
    table, onehot = `k <= 65536` unless long_lanes admits 131072."""
    from cpgisland_tpu.ops import fb_pallas

    dense = set(fb_pallas._LANE_RATE)
    oh = set(fb_pallas._LANE_RATE_ONEHOT)
    assert {
        k for k in dense if memmodel.lane_feasible(k)
    } == dense
    assert {
        k for k in oh if memmodel.lane_feasible(k, onehot=True)
    } == {k for k in oh if k <= 65536}
    assert {
        k for k in oh
        if memmodel.lane_feasible(k, onehot=True, long_lanes=True)
    } == oh


def test_pick_lane_T_values_unchanged(tmp_path):
    """End-to-end routing parity on a sweep of input sizes: the shipped
    picks (the legacy filter's) must be reproduced exactly.  Pinned with
    the graftune winner table ABSENT — this is the fallback arm every
    consulting router must reproduce bit-for-bit (tuned winners are
    test_graftune's subject)."""
    from cpgisland_tpu import tune
    from cpgisland_tpu.ops import fb_pallas

    def legacy(n, onehot, long_lanes):
        rates = (
            fb_pallas._LANE_RATE_ONEHOT if onehot else fb_pallas._LANE_RATE
        )
        if onehot and not long_lanes:
            rates = {k: v for k, v in rates.items() if k <= 65536}

        def est(lt):
            n_lanes = -(-max(n, 1) // lt)
            grid = -(-n_lanes // fb_pallas.LANE_TILE) * fb_pallas.LANE_TILE
            return grid * lt / rates[lt]

        return min(sorted(rates, reverse=True), key=est)

    sizes = [1, 4096, 1 << 20, 16 << 20, 64 << 20, 100 << 20, 320 << 20]
    tune.set_table_path(str(tmp_path / "absent-TUNING.json"))
    try:
        for n in sizes:
            for onehot in (False, True):
                for long_lanes in ((False, True) if onehot else (False,)):
                    assert fb_pallas.pick_lane_T(
                        n, onehot=onehot, long_lanes=long_lanes
                    ) == legacy(n, onehot, long_lanes), (n, onehot, long_lanes)
    finally:
        tune.set_table_path(None)
        tune.generation()


def test_seq_shard_budget_is_model_derived_and_unchanged():
    from cpgisland_tpu.train import backends

    assert memmodel.max_seq_shard() == 112 << 20
    assert backends.SEQ_SHARD_BUDGET == 112 << 20
    assert backends.SEQ_SHARD_BUDGET == memmodel.max_seq_shard()


# -- cap reconciliation: predicted limits bracket the measured ones ----------


def test_onehot_assembly_lane_cap_brackets_measured():
    """Measured (CLAUDE.md r4): the exact-EM XLA assembly compiled at
    65536 lanes and failed remote compile at 131072.  The model's
    predicted cap must land inside [65536, 131072)."""
    k = memmodel.Knobs(lane_tile=256)
    assert memmodel.feasible(
        "assembly.seqstats.onehot", k.replace(lane_T=65536)
    ).ok
    assert not memmodel.feasible(
        "assembly.seqstats.onehot", k.replace(lane_T=131072)
    ).ok


def test_vmap_decode_block_cap_brackets_measured():
    """Measured (CLAUDE.md r5): the vmap batched-decode route ran 16
    records at the default bk=4096 and failed scoped-VMEM compile at
    bk >= 8192.  Predicted cap must be exactly inside [4096, 8192)."""
    assert memmodel.max_vmap_block() == 4096
    assert memmodel.feasible("decode.vmap.onehot", block_size=4096).ok
    assert not memmodel.feasible("decode.vmap.onehot", block_size=8192).ok


def test_flat_decode_block_cap_pinned_note():
    """PINNED DISCREPANCY NOTE, not a silent pass: the single-stream flat
    route's own predicted cap is 8192 — ONE notch above the measured
    bk>=8192 failure, which was observed on the VMAP route (batch-wide
    slabs), not the flat one.  The flat route has never been driven at
    8192 on chip; if a capture ever contradicts the model, recalibrate
    memmodel.DOUBLE/_k_decode_* rather than editing this test blind."""
    assert memmodel.max_flat_block(scores=True) == 8192
    assert memmodel.max_flat_block(scores=False) == 8192
    # The shipped default stays comfortably inside the model.
    assert memmodel.flat_block_feasibility(4096).ok


def test_onehot_states_envelope_brackets_shipped():
    """fb_onehot.ONEHOT_MAX_STATES = 32 is the shipped envelope (the
    dinuc member's K); the model must admit 32 and reject the next
    power of two at the production 256-lane tile."""
    from cpgisland_tpu.ops.fb_onehot import ONEHOT_MAX_STATES

    assert memmodel.max_onehot_states() == ONEHOT_MAX_STATES == 32
    k = memmodel.Knobs(lane_tile=256)
    assert memmodel.feasible(
        "fb.seqstats.onehot", k.replace(n_states=32)
    ).ok
    assert not memmodel.feasible(
        "fb.seqstats.onehot", k.replace(n_states=64)
    ).ok


def test_seq2d_lane_cap_is_perf_not_memory():
    """PINNED DISCREPANCY NOTE: the seq2d body caps lanes at 65536
    because 131072 MISPICKS there (a measured perf knee, BASELINE.md) —
    NOT a memory cliff.  The kernelized (long_lanes) path is t-tiled, so
    the model correctly admits 131072 there; the 65536 seq2d cap lives
    in the rate table / seq2d routing, and the model must not pretend to
    derive it."""
    assert memmodel.lane_feasible(131072, onehot=True, long_lanes=True)


def test_seq_shard_model_is_conservative_by_under_one_granule():
    """Measured: a 120 Mi shard compiled and RAN; the model floors at
    112 Mi (the shipped budget).  The conservatism is bounded by one
    16 Mi granule — a documented margin, not an error."""
    assert memmodel.seq_shard_bytes(112 << 20) <= memmodel.hbm_limit()
    assert memmodel.seq_shard_bytes(128 << 20) > memmodel.hbm_limit()
    raw_cap = memmodel.hbm_limit() // memmodel.seq_shard_bytes_per_symbol()
    assert (120 << 20) - raw_cap < memmodel.SEQ_SHARD_GRANULE


# -- the routing gates -------------------------------------------------------


def test_flat_block_gate_is_noop_off_tpu():
    from cpgisland_tpu.ops import viterbi_onehot

    assert jax.default_backend() != "tpu"
    viterbi_onehot._check_flat_block(1 << 20, scores=True, stacked_m=8)


def test_flat_block_gate_raises_on_tpu(monkeypatch):
    from cpgisland_tpu.ops import viterbi_onehot

    monkeypatch.setattr(viterbi_onehot, "_interpret", lambda: False)
    viterbi_onehot._check_flat_block(4096, scores=True)  # shipped: fits
    with obs.observe() as ob:
        with pytest.raises(ValueError, match="path_out|dmax_out"):
            viterbi_onehot._check_flat_block(8192, scores=True,
                                             stacked_m=3)
    rej = [e for e in ob.events if e["event"] == "mem_reject"]
    assert rej and rej[0]["site"] == "decode_flat_block"
    assert rej[0]["max_fit_block"] == 2048


def test_stacked_gate_matches_block_cap(monkeypatch):
    from cpgisland_tpu.ops import viterbi_onehot

    monkeypatch.setattr(viterbi_onehot, "_interpret", lambda: False)
    cap = memmodel.stacked_block_cap(3, scores=True)
    assert cap == 2048
    viterbi_onehot._check_flat_block(cap, scores=True, stacked_m=3)
    with pytest.raises(ValueError, match=str(cap)):
        viterbi_onehot._check_flat_block(cap * 2, scores=True, stacked_m=3)


def test_stacked_block_clamps_on_tpu(monkeypatch):
    """The stacked decoder must CLAMP to the model cap on TPU (not trip
    the guard) — otherwise every >=3-model stacked flush at the shipped
    default bk=4096 would degrade to sequential dispatch, losing the
    PR 12 occupancy win on the hardware it targets."""
    from cpgisland_tpu.ops import viterbi_onehot

    # Off-TPU: no clamp (bit-identity tests compare at the same block).
    assert viterbi_onehot._stacked_block_for(3, 4096, True) == 4096
    monkeypatch.setattr(viterbi_onehot, "_interpret", lambda: False)
    with obs.observe() as ob:
        assert viterbi_onehot._stacked_block_for(3, 4096, True) == 2048
        assert viterbi_onehot._stacked_block_for(3, 4096, False) == 2048
        assert viterbi_onehot._stacked_block_for(2, 4096, True) == 4096
        # The clamped block passes the backstop guard.
        viterbi_onehot._check_flat_block(2048, scores=True, stacked_m=3)
    clamps = [e for e in ob.events if e["event"] == "mem_clamp"]
    assert clamps and clamps[0]["clamped"] == 2048


def test_trace_free_mem_pass_still_diffs_kernels():
    """run_mem_pass(trace=False) — bench's on-TPU parity mode — must diff
    the closed-form kernel rows against the committed lockfile (they are
    platform-independent arithmetic), not skip diffing entirely."""
    rep = mem_contracts.run_mem_pass(trace=False)
    assert rep["ok"], rep["diff"]["violations"]
    assert rep["diff"]["kernels_checked"] >= 24
    assert rep["diff"]["checked"] == 0  # no liveness entries traced
    # Re-baselining without traces would ERASE the entries section.
    with pytest.raises(ValueError, match="EMPTY entries"):
        mem_contracts.run_mem_pass(update=True, trace=False)
    lock = mem_contracts.load_lockfile()
    bad = mem_contracts.diff_kernels_only(
        lock, "cpu",
        kernels={"decode.products.dense": {"total": 1, "buffers": {}}},
    )
    assert not bad.ok
    assert any("modeled VMEM" in v for v in bad.violations)


def test_vmap_route_gate_raises_on_tpu(monkeypatch):
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.ops import viterbi_parallel

    monkeypatch.setattr(
        viterbi_parallel.jax, "default_backend", lambda: "tpu"
    )
    params = presets.durbin_cpg8()
    chunks = jnp.zeros((2, 16), jnp.int32)
    lengths = jnp.full(2, 16, jnp.int32)
    with pytest.raises(ValueError, match="vmap route"):
        viterbi_parallel.viterbi_parallel_batch(
            params, chunks, lengths, block_size=8192, engine="onehot",
            vmap_records=True,
        )


# -- mem_reject events (actionable numbers on rejection) ---------------------


def test_seq_shard_reject_emits_mem_reject_with_numbers():
    from cpgisland_tpu.train import backends

    with obs.observe() as ob:
        with pytest.raises(ValueError, match="max fit"):
            backends._check_seq_shard(
                backends.SEQ_SHARD_BUDGET + 1, "SeqBackend"
            )
    by_name = {}
    for e in ob.events:
        by_name.setdefault(e["event"], []).append(e)
    assert "seq_shard_budget_reject" in by_name  # the legacy event stays
    (rej,) = by_name["mem_reject"]
    assert rej["site"] == "seq_shard"
    assert rej["predicted_bytes"] == memmodel.seq_shard_bytes(
        backends.SEQ_SHARD_BUDGET + 1
    )
    assert rej["max_fit_symbols"] == 112 << 20


def test_island_cap_ceiling_emits_mem_reject():
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.ops.islands_device import IslandCapOverflow

    e = IslandCapOverflow(pipeline.ISLAND_CAP_CEILING + 1, 1024)
    with obs.observe() as ob:
        with pytest.raises(IslandCapOverflow):
            pipeline._grow_cap_or_raise(e, [1024])
    (rej,) = [x for x in ob.events if x["event"] == "mem_reject"]
    assert rej["site"] == "island_cap"
    assert rej["predicted_bytes"] == memmodel.island_columns_bytes(
        pipeline.ISLAND_CAP_CEILING + 1
    )
    assert rej["max_fit_calls"] == pipeline.ISLAND_CAP_CEILING


def test_island_cap_retry_event_carries_predicted_bytes():
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.ops.islands_device import IslandCapOverflow

    box = [1024]
    with obs.observe() as ob:
        pipeline._grow_cap_or_raise(IslandCapOverflow(3000, 1024), box)
    (ev,) = [x for x in ob.events if x["event"] == "island_cap_retry"]
    assert box[0] == 4096
    assert ev["predicted_bytes"] == memmodel.island_columns_bytes(4096)


# -- lockfile mechanics ------------------------------------------------------


def _fp(peak_ps=100.0, peak_fixed=1000.0, wb_ps=50.0, lin=None):
    m = {
        "peak_bytes": 10000, "arg_bytes": 400, "out_bytes": 40,
        "alloc_bytes": 9000, "while_body_peak": 5000,
    }
    return {
        "geometries": [100, 200],
        "metrics": [m, m],
        "fits": {
            "peak_bytes": {"per_symbol": peak_ps, "fixed": peak_fixed},
            "alloc_bytes": {"per_symbol": 80.0, "fixed": 500.0},
            "while_body_peak": {"per_symbol": wb_ps, "fixed": 100.0},
        },
        "linear_groups": list(lin or [["a.py:fn", 42.0]]),
    }


def _kernel_row(total=1000):
    return {"total": total, "limit": memmodel.vmem_limit(),
            "headroom": 0.9, "buffers": {"pair": total}}


def _lock_for(fp, kernels=None):
    return {
        "version": 1,
        "tolerances": {},
        "platforms": {"cpu": {
            "jax": "x", "entries": {"e": fp},
            "kernels": dict(kernels or {"k": _kernel_row()}),
        }},
    }


def _diff(live_fp, lock, kernels=None):
    return mem_contracts.diff_mem(
        {"e": live_fp}, lock, "cpu",
        kernels=dict(kernels or {"k": _kernel_row()}),
    )


def test_mem_diff_inside_tolerance_passes():
    diff = _diff(_fp(peak_ps=101.9), _lock_for(_fp(peak_ps=100.0)))
    assert diff.ok, diff.violations


def test_mem_diff_past_tolerance_fails():
    diff = _diff(_fp(peak_ps=102.5), _lock_for(_fp(peak_ps=100.0)))
    assert not diff.ok
    assert any("peak_bytes.per_symbol" in v for v in diff.violations)


def test_mem_diff_while_body_drift_fails():
    diff = _diff(_fp(wb_ps=55.0), _lock_for(_fp(wb_ps=50.0)))
    assert not diff.ok
    assert any("while_body_peak" in v for v in diff.violations)


def test_mem_diff_linear_group_drift_names_group():
    diff = _diff(
        _fp(lin=[["a.py:fn", 42.0], ["islands.py:body", 40.0]]),
        _lock_for(_fp()),
    )
    assert not diff.ok
    assert any(
        "O(T) allocation groups drifted" in v and "islands.py:body" in v
        for v in diff.violations
    )


def test_mem_diff_linear_group_slope_drift_caught():
    diff = _diff(
        _fp(lin=[["a.py:fn", 44.0]]),
        _lock_for(_fp(lin=[["a.py:fn", 42.0]])),
    )
    assert not diff.ok
    assert any(
        "O(T) group a.py:fn slope" in v for v in diff.violations
    ), diff.violations


def test_mem_diff_kernel_vmem_is_exact_and_names_buffers():
    diff = _diff(
        _fp(), _lock_for(_fp()),
        kernels={"k": _kernel_row(total=1001)},
    )
    assert not diff.ok
    assert any(
        "kernel k" in v and "pair" in v for v in diff.violations
    )


def test_mem_diff_stale_entry_reported_not_failed():
    lock = _lock_for(_fp())
    diff = mem_contracts.diff_mem(
        {}, lock, "cpu", kernels={"k": _kernel_row()}
    )
    assert diff.stale == ["e"]
    assert any("stale lockfile entry" in n for n in diff.notes)
    assert diff.ok


def test_mem_diff_missing_entry_is_violation():
    lock = _lock_for(_fp())
    diff = mem_contracts.diff_mem(
        {"e": _fp(), "new": _fp()}, lock, "cpu",
        kernels={"k": _kernel_row()},
    )
    assert not diff.ok
    assert any("new: not in the lockfile" in v for v in diff.violations)


def test_mem_diff_missing_platform_is_note_not_violation():
    diff = mem_contracts.diff_mem({"e": _fp()}, _lock_for(_fp()), "tpu")
    assert diff.ok
    assert any("no 'tpu' section" in n for n in diff.notes)


@pytest.mark.slow
def test_cli_update_mem_round_trip(tmp_path):
    lockfile = str(tmp_path / "MEMORY.json")
    # 1. Baseline: --update-mem writes the lockfile and exits 0.
    proc = _run_cli("--no-lint", "--update-mem", "--mem-file", lockfile)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "re-baselined" in proc.stderr
    # 2. A clean re-run diffs green against it.
    proc = _run_cli("--no-lint", "--mem", "--mem-file", lockfile)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    # 3. Tamper with one pinned fit: the diff fails naming the metric.
    data = json.load(open(lockfile))
    entries = data["platforms"]["cpu"]["entries"]
    name = sorted(entries)[0]
    entries[name]["fits"]["peak_bytes"]["per_symbol"] *= 1.5
    json.dump(data, open(lockfile, "w"))
    proc = _run_cli("--no-lint", "--mem", "--mem-file", lockfile)
    assert proc.returncode == 1
    assert "peak_bytes.per_symbol" in proc.stdout
    # 4. --update-mem re-baselines back to green and prints what moved.
    proc = _run_cli("--no-lint", "--update-mem", "--mem-file", lockfile)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    proc = _run_cli("--no-lint", "--mem", "--mem-file", lockfile)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)


def test_mem_table_cli_names_buffers():
    proc = _run_cli("--mem-table", "decode.backpointers.onehot.scores")
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "dmax_out" in proc.stdout
    assert "**total**" in proc.stdout
    proc = _run_cli("--mem-table", "decode.nope")
    assert proc.returncode == 2
