"""graftscope (PR 16): request-scoped serve telemetry.

- metrics: the fixed-layout log-binned histogram — bin math, quantile
  error bound, EXACT merge (associative integer bin adds) under 8
  concurrent writers, wire roundtrip through JSON, layout rejection.
- flight recorder: ring bounds, atomic persistence, kill-path artifact
  (the SimulatedKill postmortem file is written BEFORE the kill
  propagates — nothing downstream may catch it).
- lineage: every request admitted into a mixed multi-tenant broker
  stream ends with a closed trace whose hops are monotone in time and
  cover admit -> journal.admit -> taken -> flush.enter -> executed ->
  journal.complete -> respond, emitted as ONE request_trace event.
- zero-overhead-off: the ledger proves a telemetry-off serve stream and
  a telemetry-on one issue IDENTICAL device work (same dispatches, zero
  fresh compiles) over same-shape streams.
- wire: ``kind=stats`` answered inline (never queued) with the SLO
  snapshot, on both the stdio stream and the socket mux.
"""

import io
import json
import math
import os
import socket
import threading
import time

import numpy as np
import pytest

from cpgisland_tpu import obs, resilience
from cpgisland_tpu.analysis import tracksync
from cpgisland_tpu.models import presets
from cpgisland_tpu.obs import scope as scope_mod
from cpgisland_tpu.obs.metrics import (
    LO,
    N_BINS,
    Histogram,
    ServeMetrics,
    bin_edges,
    bin_index,
)
from cpgisland_tpu.resilience import faultplan
from cpgisland_tpu.resilience.faultplan import Fault, FaultPlan
from cpgisland_tpu.serve import BrokerConfig, RequestBroker, Session
from cpgisland_tpu.serve import transport

BASES = np.array(list("acgt"))


@pytest.fixture(autouse=True)
def _fresh_state():
    resilience.reset()
    assert scope_mod.active() is None, "a previous test leaked a Scope"
    yield
    scope_mod.uninstall()
    resilience.reset()


@pytest.fixture()
def tracker():
    # Exact-count lock assertions on a private tracker; under
    # CPGISLAND_TRACKSYNC=1 the session-wide tracker owns the factories.
    if tracksync.current() is not None:
        pytest.skip("session-wide LockTracker active (CPGISLAND_TRACKSYNC=1)")
    tr, uninstall = tracksync.install()
    try:
        yield tr
    finally:
        uninstall()


def _gen_symbols(rng, n: int) -> np.ndarray:
    bg = rng.choice(4, size=n, p=[0.3, 0.2, 0.2, 0.3])
    k = max(1, n // 4)
    bg[:k] = rng.choice(4, size=k, p=[0.1, 0.4, 0.4, 0.1])
    return bg.astype(np.uint8)


def _mixed_recs(n=12, seed=11):
    """Mixed lengths, decode + posterior, two tenants."""
    rng = np.random.default_rng(seed)
    return [
        (
            i,
            f"rec{i}",
            "decode" if i % 3 != 1 else "posterior",
            f"t{i % 2}",
            _gen_symbols(rng, 400 + 97 * i),
        )
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# Histograms


def test_bin_index_layout_and_edges():
    assert bin_index(0.0) == 0
    assert bin_index(-5.0) == 0
    assert bin_index(float("nan")) == 0
    assert bin_index(LO) == 0
    assert bin_index(1e99) == N_BINS - 1
    for v in (1e-6, 3.7e-3, 0.25, 1.0, 512.0, 9.9e6):
        i = bin_index(v)
        lo, hi = bin_edges(i)
        assert lo <= v < hi, (v, i, lo, hi)


def test_histogram_quantile_within_bin_error_bound():
    """Quarter-octave bins: any quantile's relative error is bounded by
    the half-bin ratio 2**0.125 - 1 (~9.05%); min/max are exact."""
    h = Histogram()
    for i in range(1, 1000):  # 1..999 ms
        h.observe(i * 1e-3)
    s = h.snapshot()
    assert s["count"] == 999
    assert s["min"] == 1e-3 and s["max"] == 999e-3
    assert abs(s["sum"] - sum(i * 1e-3 for i in range(1, 1000))) < 1e-9
    for q, true in ((0.50, 0.500), (0.95, 0.950), (0.99, 0.990)):
        est = h.quantile(q)
        assert abs(est - true) / true < 0.095, (q, est)


def test_histogram_merge_exact_and_associative_under_threads(tracker):
    """8 concurrent writers into one shared histogram AND one private
    histogram each: the shared result equals the merge of the privates
    BIN-FOR-BIN (integer adds — exact), and merging in two different
    association orders yields identical wire forms."""
    N_THREADS, N_VALS = 8, 2000
    shared = Histogram()
    parts = [Histogram() for _ in range(N_THREADS)]
    # Deterministic per-thread values spanning ~8 octaves.
    vals = [
        [1e-6 * (1.17 ** ((i * N_VALS + j) % 97)) for j in range(N_VALS)]
        for i in range(N_THREADS)
    ]
    start = threading.Barrier(N_THREADS)

    def worker(i):
        start.wait()
        for v in vals[i]:
            shared.observe(v)
            parts[i].observe(v)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(N_THREADS)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()

    total = N_THREADS * N_VALS
    assert shared.count == total

    left = Histogram()
    for p in parts:  # left fold
        left.merge(p)
    right = Histogram()
    for p in reversed(parts):  # different association order
        right.merge(p)

    for merged in (left, right):
        mw, sw = merged.to_wire(), shared.to_wire()
        assert mw["bins"] == sw["bins"]  # exact: integer bin adds
        assert mw["count"] == sw["count"] == total
        assert mw["min"] == sw["min"] and mw["max"] == sw["max"]
        # Sums differ only by float addition order.
        assert math.isclose(mw["sum"], sw["sum"], rel_tol=1e-9)
    assert left.to_wire()["bins"] == right.to_wire()["bins"]


def test_histogram_wire_roundtrip_through_json():
    h = Histogram()
    for v in (1e-4, 3e-4, 0.02, 0.02, 7.5):
        h.observe(v)
    back = Histogram.from_wire(json.loads(json.dumps(h.to_wire())))
    assert back.snapshot() == h.snapshot()
    assert back.to_wire() == h.to_wire()
    # A wire histogram merges exactly like a local one.
    acc = Histogram()
    acc.merge(back)
    acc.merge(back)
    assert acc.count == 2 * h.count
    # Layout drift is rejected, never silently misbinned.
    bad = h.to_wire()
    bad["layout"] = dict(bad["layout"], log2_growth=0.5)
    with pytest.raises(ValueError, match="layout"):
        Histogram.from_wire(bad)
    # Empty histograms roundtrip too (min/max are None on the wire).
    assert Histogram.from_wire(Histogram().to_wire()).snapshot()["count"] == 0


def test_servemetrics_merge_and_wire_roundtrip():
    a, b = ServeMetrics(), ServeMetrics()
    a.note_result(tenant="t0", model="", device="dev0", n_symbols=100,
                  latency_s=0.010)
    b.note_result(tenant="t0", model="m1", device="dev1", n_symbols=50,
                  latency_s=0.020)
    b.note_flush(n_requests=2, symbols=150, wall_s=0.005)
    a.merge(ServeMetrics.from_wire(json.loads(json.dumps(b.to_wire()))))
    snap = a.snapshot()
    assert snap["latency_s"]["count"] == 2
    assert snap["flush_requests"]["count"] == 1
    thr = snap["throughput"]
    assert thr["tenant"]["t0"] == {"requests": 2, "symbols": 150}
    assert thr["device"]["dev0"]["requests"] == 1
    assert thr["device"]["dev1"]["requests"] == 1
    assert thr["model"]["-"]["requests"] == 1  # unmodeled bucket
    assert thr["model"]["m1"]["requests"] == 1


# ---------------------------------------------------------------------------
# Flight recorder


def test_flight_recorder_ring_bounds_and_atomic_persist(tmp_path):
    cap = 32
    path = str(tmp_path / "serve.flight.json")
    rec = scope_mod.FlightRecorder(capacity=cap, path=path)
    for i in range(3 * cap):
        rec.record("tick", n=i)
    st = rec.stats()
    assert st["events"] == cap and st["seen"] == 3 * cap
    ring = rec.snapshot()
    assert len(ring) == cap
    assert [e["n"] for e in ring] == list(range(2 * cap, 3 * cap))  # last N
    assert rec.persist("unit") == path
    dump = json.load(open(path))
    assert dump["reason"] == "unit" and dump["pid"] == os.getpid()
    assert dump["events_seen"] == 3 * cap and dump["capacity"] == cap
    assert [e["n"] for e in dump["events"]] == list(range(2 * cap, 3 * cap))
    # No tmp litter (tmp + fsync + os.replace).
    assert os.listdir(tmp_path) == ["serve.flight.json"]
    # Pathless recorders are inert, and an unwritable path is best-effort.
    assert scope_mod.FlightRecorder(capacity=4).persist("x") is None
    assert rec.persist("x", path=str(tmp_path / "no/such/dir/f.json")) is None


def test_scope_kill_persists_flight_artifact_before_raise(tmp_path):
    """graftfault SimulatedKill at flush.enter: the postmortem artifact is
    written BEFORE the kill propagates (nothing between the injection
    point and the harness may catch it), and it names the kill site."""
    params = presets.durbin_cpg8()
    sess = Session(params, name="killscope", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 20, flush_deadline_s=0.0)
    )
    fpath = str(tmp_path / "serve.flight.json")
    sc = scope_mod.install(scope_mod.Scope(flight_path=fpath))
    plan = FaultPlan([Fault("flush.enter", kind="kill", nth=1)],
                     name="kill-mid-flush")
    rng = np.random.default_rng(5)
    killed = False
    try:
        with faultplan.active(plan):
            try:
                for rid in range(3):
                    broker.submit(request_id=rid, tenant="a", kind="decode",
                                  symbols=_gen_symbols(rng, 500 + 70 * rid),
                                  name=f"r{rid}")
                for _ in broker.drain():
                    pass
            except faultplan.SimulatedKill:
                killed = True
    finally:
        scope_mod.uninstall(sc)
    assert killed, "the kill plan never fired"
    dump = json.load(open(fpath))
    assert dump["reason"] == "kill:flush.enter"
    kinds = [e["kind"] for e in dump["events"]]
    assert kinds[-1] == "kill"
    assert dump["events"][-1]["point"] == "flush.enter"
    inj = [e for e in dump["events"] if e["kind"] == "graftfault_injected"]
    assert inj and inj[-1]["fault_kind"] == "kill"
    assert inj[-1]["plan"] == "kill-mid-flush"


# ---------------------------------------------------------------------------
# Lineage completeness


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_lineage_complete_over_mixed_multi_tenant_stream(tmp_path):
    """Every request admitted into a mixed multi-tenant journaled stream
    ends with exactly one closed trace: hops monotone in time, first hop
    admit, last respond, journal/queue/flush stations all present; one
    request_trace event per request lands in the metrics stream; the SLO
    rollup covers the whole stream exactly."""
    params = presets.durbin_cpg8()
    sess = Session(params, name="lineage", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=3000, flush_deadline_s=0.0),
        manifest_path=str(tmp_path / "j.jsonl"),
    )
    recs = _mixed_recs(12)
    sc = scope_mod.install(scope_mod.Scope())
    try:
        with obs.observe() as ob:
            for rid, nm, kind, ten, syms in recs:
                broker.submit(request_id=rid, tenant=ten, kind=kind,
                              symbols=syms, name=nm)
            results = {r.id: r for r in broker.drain()}
    finally:
        scope_mod.uninstall(sc)
    broker.close()
    assert all(r.ok for r in results.values())
    assert broker.flushes >= 2  # the stream really coalesced into flushes

    snap = sc.snapshot()
    assert snap["open_requests"] == 0
    assert snap["completed_requests"] == len(recs)
    assert snap["dropped_traces"] == 0
    traces = {tr["id"]: tr for tr in sc.traces}
    assert sorted(traces) == [rid for rid, *_ in recs]
    for rid, nm, kind, ten, syms in recs:
        tr = traces[rid]
        hops = [h["hop"] for h in tr["hops"]]
        assert hops[0] == "admit" and hops[-1] == "respond", hops
        for must in ("journal.admit", "taken", "flush.enter", "executed",
                     "journal.complete"):
            assert must in hops, (rid, hops)
        assert hops.count("flush.enter") == 1  # no requeues here
        stamps = [h["t"] for h in tr["hops"]]
        assert stamps == sorted(stamps)  # append order IS timestamp order
        assert tr["tenant"] == ten and tr["kind"] == kind
        assert tr["n_symbols"] == syms.size
        assert tr["ok"] and tr["route"]
        assert tr["latency_s"] > 0.0
        # flush membership is consistent between the two flush hops.
        fe = next(h for h in tr["hops"] if h["hop"] == "flush.enter")
        ex = next(h for h in tr["hops"] if h["hop"] == "executed")
        assert fe["flush"] == ex["flush"]

    # Exactly ONE request_trace event per request reached the obs stream.
    evs = [e for e in ob.events if e["event"] == "request_trace"]
    assert sorted(e["id"] for e in evs) == sorted(traces)
    assert all(e["hops"] for e in evs)

    # SLO rollup: exact stream coverage.
    m = sc.metrics.snapshot()
    assert m["latency_s"]["count"] == len(recs)
    assert m["flush_requests"]["count"] == broker.flushes
    total = sum(s.size for *_, s in recs)
    thr = m["throughput"]
    assert set(thr["tenant"]) == {"t0", "t1"}
    assert sum(v["symbols"] for v in thr["tenant"].values()) == total
    assert sum(v["requests"] for v in thr["tenant"].values()) == len(recs)

    # The report renderer walks these traces (smoke: every id shows up).
    from cpgisland_tpu.obs import report

    text = report.render_lineage(sc.traces)
    for rid, *_ in recs:
        assert f"request {rid} " in text
    assert "flush composition:" in text
    assert "request 999: no trace in this stream" in report.render_lineage(
        sc.traces, 999
    )


def test_telemetry_off_serve_path_is_dispatch_identical():
    """The acceptance gate: with telemetry OFF the serve path must issue
    ZERO additional blocking dispatches or compiles versus telemetry ON —
    ledger-asserted over same-shape streams (warm first, then compare)."""
    params = presets.durbin_cpg8()
    sess = Session(params, name="zcost", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=4000, flush_deadline_s=0.0)
    )
    rng = np.random.default_rng(2)
    streams = [_gen_symbols(rng, 500 + 37 * i) for i in range(6)]

    def run(base):
        with obs.observe() as ob:
            for i, s in enumerate(streams):
                broker.submit(request_id=base + i, tenant="a", kind="decode",
                              symbols=s, name=f"r{i}")
            res = broker.drain()
        assert all(r.ok for r in res) and len(res) == len(streams)
        return ob.ledger.totals()

    run(0)  # warm: compiles happen here
    assert not scope_mod.enabled()
    off = run(100)  # telemetry OFF
    sc = scope_mod.install(scope_mod.Scope())
    try:
        on = run(200)  # telemetry ON, same geometries
    finally:
        scope_mod.uninstall(sc)
    assert off["compiles"] == 0 and on["compiles"] == 0
    assert on["dispatches"] == off["dispatches"]
    assert on["upload_bytes"] == off["upload_bytes"]
    # ... and the ON run really captured the stream.
    assert sc.snapshot()["completed_requests"] == len(streams)
    broker.close()


# ---------------------------------------------------------------------------
# kind=stats wire


def test_stats_wire_request_answers_inline_with_slo():
    params = presets.durbin_cpg8()
    sess = Session(params, name="statw", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=100, flush_deadline_s=0.0)
    )
    rng = np.random.default_rng(3)
    syms = _gen_symbols(rng, 700)
    lines = [
        json.dumps({"id": 1, "kind": "decode",
                    "seq": "".join(BASES[syms]), "tenant": "t0"}),
        json.dumps({"id": 2, "kind": "stats"}),
        json.dumps({"op": "shutdown"}),
    ]
    sc = scope_mod.install(scope_mod.Scope())
    try:
        out = io.StringIO()
        served = transport.serve_stream(
            io.StringIO("\n".join(lines) + "\n"), out, broker,
            use_worker=False,
        )
    finally:
        scope_mod.uninstall(sc)
    resp = {o.get("id"): o for o in map(json.loads,
                                        out.getvalue().splitlines())}
    assert resp[1]["ok"]
    st = resp[2]
    assert st["ok"] and st["kind"] == "stats"
    # The decode flushed before the stats line was read (tiny budget,
    # inline worker): the SLO snapshot already covers it.
    lat = st["slo"]["metrics"]["latency_s"]
    assert lat["count"] == 1 and lat["p50"] > 0.0
    assert st["slo"]["open_requests"] == 0
    assert st["slo"]["metrics"]["throughput"]["tenant"]["t0"]["requests"] == 1
    assert st["stats"]["flushes"] >= 1
    # A stats poll never enters the flush queue — it is not "served".
    assert served == 1
    # The whole response is JSON-clean by construction (it round-tripped
    # through the StringIO wire above); scope-off answers slo=None.
    off = transport._stats_wire({"id": 9}, broker)
    assert off["slo"] is None and off["ok"] and off["id"] == 9


@pytest.mark.slow
def test_mux_stream_lineage_and_stats_roundtrip(tmp_path):
    """Socket mux: a mixed multi-tenant stream over one connection closes
    every trace; a second connection's kind=stats poll sees the rollup
    plus mux routing stats."""
    from cpgisland_tpu.serve.transport import serve_socket

    params = presets.durbin_cpg8()
    sess = Session(params, name="muxscope", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=2500, flush_deadline_s=0.05)
    )
    sock_path = str(tmp_path / "s.sock")
    recs = _mixed_recs(6, seed=19)
    requests = [
        {"id": 100 + rid, "kind": kind, "seq": "".join(BASES[syms]),
         "name": nm, "tenant": ten}
        for rid, nm, kind, ten, syms in recs
    ]

    def client(reqs):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(sock_path)
        rf = s.makefile("r", encoding="utf-8")
        wf = s.makefile("w", encoding="utf-8")
        want = set()
        for req in reqs:
            wf.write(json.dumps(req) + "\n")
            want.add(req["id"])
        wf.flush()
        got = {}
        for line in rf:
            o = json.loads(line)
            if o.get("id") in want:
                got[o["id"]] = o
            if set(got) == want:
                break
        s.close()
        return got

    sc = scope_mod.install(scope_mod.Scope())
    try:
        server = threading.Thread(target=serve_socket,
                                  args=(sock_path, broker), daemon=True)
        server.start()
        deadline = time.monotonic() + 30.0
        while not os.path.exists(sock_path):
            assert time.monotonic() < deadline
            time.sleep(0.01)
        while True:
            try:
                probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                probe.connect(sock_path)
                probe.close()
                break
            except OSError:
                assert time.monotonic() < deadline
                time.sleep(0.05)
        responses = client(requests)
        st = client([{"id": 999, "kind": "stats"}])[999]
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(sock_path)
        s.sendall(b'{"op": "shutdown"}\n')
        s.close()
        server.join(timeout=60.0)
        assert not server.is_alive()
    finally:
        scope_mod.uninstall(sc)

    assert all(r["ok"] for r in responses.values())
    traces = {tr["id"]: tr for tr in sc.traces}
    assert sorted(traces) == sorted(r["id"] for r in requests)
    for req in requests:
        tr = traces[req["id"]]
        hops = [h["hop"] for h in tr["hops"]]
        assert hops[0] == "admit" and hops[-1] == "respond"
        assert "taken" in hops and "flush.enter" in hops
        stamps = [h["t"] for h in tr["hops"]]
        assert stamps == sorted(stamps)
        assert tr["tenant"] == req["tenant"]
    assert st["ok"] and st["kind"] == "stats"
    assert st["slo"]["metrics"]["latency_s"]["count"] == len(requests)
    assert set(st["slo"]["metrics"]["throughput"]["tenant"]) == {"t0", "t1"}
    assert "mux" in st  # the router's routing stats ride along


# ---------------------------------------------------------------------------
# Snapshot emitter (--metrics-interval)


def test_snapshot_emitter_emits_slo_records_and_stops():
    sc = scope_mod.Scope()
    sc.metrics.note_result(tenant="a", model="", device="dev0",
                           n_symbols=10, latency_s=0.001)
    seen = []
    em = scope_mod.SnapshotEmitter(
        sc, interval_s=3600.0, extra_fn=lambda: {"stats": {
            "queued_requests": 7}})
    with obs.observe() as ob:
        em.emit_once()  # deterministic: no timer dependence
    em.stop()  # never started: stop() is a no-op join
    seen = [e for e in ob.events if e["event"] == "slo_snapshot"]
    assert len(seen) == 1
    assert seen[0]["slo"]["latency_s"]["count"] == 1
    assert seen[0]["stats"]["queued_requests"] == 7
    ring = sc.recorder.snapshot()
    assert ring[-1]["kind"] == "snapshot"
    assert ring[-1]["requests"] == 1 and ring[-1]["queued_requests"] == 7

    # The threaded path: a short interval emits at least once, then joins.
    em2 = scope_mod.SnapshotEmitter(sc, interval_s=0.01).start()
    deadline = time.monotonic() + 10.0
    while sc.recorder.stats()["seen"] < 3:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    em2.stop()
    assert em2._thread is None
