"""Vectorized island caller vs the reference-semantics oracle state machine."""

import numpy as np
import pytest

from cpgisland_tpu.ops import islands as I
from tests import oracle


def _random_paths(rng, n=200, maxlen=400):
    for _ in range(n):
        T = int(rng.integers(1, maxlen))
        # Mix of regimes to generate many island open/close events.
        p = rng.integers(0, 8, size=T)
        yield p


def test_fuzz_matches_oracle(rng):
    checked = emitted = 0
    for path in _random_paths(rng):
        got = I.call_islands(path, chunk=0).as_tuples()
        want = oracle.islands_oracle(path)
        assert len(got) == len(want), f"count mismatch on path len {len(path)}"
        for g, w in zip(got, want):
            assert g[0] == w[0] and g[1] == w[1] and g[2] == w[2]
            assert g[3] == pytest.approx(w[3])
            assert g[4] == pytest.approx(w[4])
        checked += 1
        emitted += len(got)
    assert emitted > 50  # the fuzz actually exercised emissions


def test_structured_runs_match_oracle(rng):
    # Longer runs (islands of length ~50) rather than white noise.
    for _ in range(30):
        segs = []
        for _s in range(rng.integers(2, 10)):
            state = int(rng.integers(0, 8))
            segs.append(np.full(rng.integers(1, 60), state))
        path = np.concatenate(segs)
        got = I.call_islands(path).as_tuples()
        want = oracle.islands_oracle(path)
        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert g[:3] == w[:3]
            assert g[3] == pytest.approx(w[3])
            assert g[4] == pytest.approx(w[4])


def test_chunk_offset_matches_oracle(rng):
    path = np.asarray([4, 1, 2, 1, 2, 4])
    got = I.call_islands(path, chunk=3).as_tuples()
    want = oracle.islands_oracle(path, chunk=3)
    assert got == [
        (w[0], w[1], w[2], pytest.approx(w[3]), pytest.approx(w[4])) for w in want
    ]
    assert got[0][0] == 1 + 3 * 0x100000 + 1


def test_stale_atc_quirk_compat_vs_clean():
    # C+-island closes, new island opens on A+ then G+: compat counts a stale
    # CpG (java:325-331 never clears atC on non-C opening); clean must not.
    path = np.asarray([1, 1, 2, 1] + [4] + [0, 2, 2, 1, 2] + [4])
    compat = I.call_islands(path, compat=True)
    clean = I.call_islands(path, compat=False)
    want = oracle.islands_oracle(path)
    assert compat.as_tuples()[-1][4] == pytest.approx(want[-1][4])
    # island 2: len 5, c=1, g=3; compat cg = stale(1)+real(1)=2, clean cg=1.
    assert compat.oe_ratio[-1] == pytest.approx(2 * 5 / (1 * 3))
    assert clean.oe_ratio[-1] == pytest.approx(1 * 5 / (1 * 3))


def test_open_at_end_compat_vs_clean():
    path = np.asarray([4, 4] + [1, 2] * 30)
    assert len(I.call_islands(path, compat=True)) == 0  # reference drops it
    clean = I.call_islands(path, compat=False)
    assert len(clean) == 1
    assert clean.end[0] == len(path)  # 1-based inclusive end == T


def test_min_len_filter_clean_only():
    path = np.asarray([4] + [1, 2] * 10 + [4])  # 20 bp island
    assert len(I.call_islands(path, compat=False, min_len=200)) == 0
    assert len(I.call_islands(path, compat=False, min_len=None)) == 1
    # compat ignores min_len (reference has it commented out, java:285)
    assert len(I.call_islands(path, compat=True)) == 1


def test_format_lines_reference_format():
    path = np.asarray([4] + [1, 2] * 10 + [4])
    out = I.call_islands(path).format_lines()
    assert out == "2 21 20 1.000000 2.000000\n"


def test_empty_and_all_background():
    assert len(I.call_islands(np.zeros(0, dtype=np.int64))) == 0
    assert len(I.call_islands(np.full(100, 5))) == 0


def test_concatenate():
    a = I.call_islands(np.asarray([4, 1, 2, 1, 2, 4]), chunk=0, chunk_size=10)
    b = I.call_islands(np.asarray([4, 1, 2, 1, 2, 4]), chunk=1, chunk_size=10)
    cat = I.IslandCalls.concatenate([a, b])
    assert len(cat) == 2 and cat.beg[1] == cat.beg[0] + 10
    assert len(I.IslandCalls.concatenate([])) == 0


# ---------------------------------------------------------------------------
# Generic-state-set caller (call_islands_obs)


def test_obs_caller_matches_8state_caller_on_consistent_paths(rng):
    """With the Durbin one-hot emissions, state X+- implies obs x, so the
    obs-based caller over island_states={0..3} must agree with the clean
    8-state caller on any consistent (path, obs) pair."""
    path = rng.integers(0, 8, size=20000).astype(np.int64)
    obs = (path % 4).astype(np.uint8)  # consistent: state X+- emitted x
    a = I.call_islands(path, compat=False)
    b = I.call_islands_obs(path, obs, island_states=range(4))
    np.testing.assert_array_equal(a.beg, b.beg)
    np.testing.assert_array_equal(a.end, b.end)
    np.testing.assert_allclose(a.gc_content, b.gc_content)
    np.testing.assert_allclose(a.oe_ratio, b.oe_ratio)


def test_obs_caller_two_state_model():
    """2-state model: island membership from the path, composition from obs."""
    # one island of 8 GC-rich positions (cgcgcgcg) in an AT background
    path = np.array([1] * 5 + [0] * 8 + [1] * 5)
    obs = np.array([0, 3, 0, 3, 0] + [1, 2, 1, 2, 1, 2, 1, 2] + [3, 0, 3, 0, 3], dtype=np.uint8)
    calls = I.call_islands_obs(path, obs, island_states=(0,))
    assert len(calls) == 1
    assert calls.beg[0] == 6 and calls.end[0] == 13
    assert calls.gc_content[0] == 1.0
    # 4 CpG dinucleotides in 8 bases with 4 C and 4 G: oe = 4*8/(4*4) = 2.0
    assert calls.oe_ratio[0] == 2.0


def test_obs_caller_open_at_end_and_offset():
    path = np.array([1, 1, 0, 0, 0, 0])
    obs = np.array([0, 0, 1, 2, 1, 2], dtype=np.uint8)
    calls = I.call_islands_obs(path, obs, island_states=(0,), offset=100)
    assert len(calls) == 1  # clean semantics: emitted even though open at end
    assert calls.beg[0] == 103 and calls.end[0] == 106


def test_obs_caller_shape_mismatch():
    with pytest.raises(ValueError):
        I.call_islands_obs(np.zeros(3, int), np.zeros(4, np.uint8), island_states=(0,))
