"""Viterbi scan vs the NumPy oracle, incl. padded chunks and batching."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops import viterbi as V
from tests import oracle


def _random_model(rng, k=3, m=4):
    pi = rng.dirichlet(np.ones(k))
    A = rng.dirichlet(np.ones(k), size=k)
    B = rng.dirichlet(np.ones(m), size=k)
    return pi, A, B


@pytest.mark.parametrize("T", [1, 2, 7, 64])
def test_matches_oracle_random_models(rng, T):
    for trial in range(5):
        pi, A, B = _random_model(rng)
        obs = rng.integers(0, 4, size=T)
        params = HmmParams.from_probs(pi, A, B)
        path, score = V.viterbi(params, jnp.asarray(obs))
        opath, oscore = oracle.viterbi_oracle(pi, A, B, obs)
        # Score must match; path must achieve it (argmax ties may differ).
        # On TPU the bound grows with T: every log A / log B term carries
        # ~2e-5 relative transcendental error.
        from conftest import tpu_atol

        assert score == pytest.approx(oscore, abs=tpu_atol(1e-3, max(1e-3, 1e-4 * T)))
        _assert_path_score(pi, A, B, obs, np.asarray(path), oscore)


def _assert_path_score(pi, A, B, obs, path, expected):
    with np.errstate(divide="ignore"):
        lp, lA, lB = np.log(pi), np.log(A), np.log(B)
    s = lp[path[0]] + lB[path[0], obs[0]]
    for t in range(1, len(obs)):
        s += lA[path[t - 1], path[t]] + lB[path[t], obs[t]]
    # The device may pick a near-tie path under its approximate scores; its
    # exact (f64) score then trails the oracle's by the same T-scaled bound.
    from conftest import tpu_atol

    assert s == pytest.approx(expected, abs=tpu_atol(1e-3, max(1e-3, 1e-4 * len(obs))))


def test_durbin_model_decodes_planted_islands(rng):
    # Background AT-rich, then a CG-rich stretch, then background again.
    params = presets.durbin_cpg8()
    bg = rng.choice([0, 3], size=300)  # a/t
    island = np.tile([1, 2], 100)  # cgcg... the strongest island signal
    obs = np.concatenate([bg, island, bg]).astype(np.int32)
    path, _ = V.viterbi(params, jnp.asarray(obs))
    path = np.asarray(path)
    mid = path[320:480]
    assert (mid < 4).mean() > 0.95  # island states dominate inside
    assert (path[:280] >= 4).mean() > 0.95  # background before
    assert (path[-280:] >= 4).mean() > 0.95


def test_padded_matches_unpadded(rng):
    pi, A, B = _random_model(rng)
    params = HmmParams.from_probs(pi, A, B)
    obs = rng.integers(0, 4, size=50)
    full_path, full_score = V.viterbi(params, jnp.asarray(obs))
    padded = np.concatenate([obs, np.full(14, 4)]).astype(np.int32)  # PAD=4
    ppath, pscore = V.viterbi_padded(params, jnp.asarray(padded), jnp.int32(50))
    assert pscore == pytest.approx(float(full_score), abs=1e-4)
    np.testing.assert_array_equal(np.asarray(ppath)[:50], np.asarray(full_path))


def test_batch_decode(rng):
    params = presets.durbin_cpg8()
    chunks = rng.integers(0, 4, size=(5, 40)).astype(np.int32)
    lengths = np.array([40, 40, 30, 40, 10], dtype=np.int32)
    chunks[2, 30:] = 4
    chunks[4, 10:] = 4
    paths, scores = V.viterbi_batch(params, jnp.asarray(chunks), jnp.asarray(lengths))
    for i in range(5):
        p, s = V.viterbi_padded(params, jnp.asarray(chunks[i]), jnp.int32(lengths[i]))
        L = lengths[i]
        np.testing.assert_array_equal(np.asarray(paths[i])[:L], np.asarray(p)[:L])
        assert float(scores[i]) == pytest.approx(float(s), abs=1e-4)


def test_single_symbol_sequence():
    params = presets.durbin_cpg8()
    path, score = V.viterbi(params, jnp.asarray([1]))
    # Most likely single state emitting 'c': argmax over pi * B[:, c];
    # pi: islands 0.05 each, background 0.2 each; one-hot B -> C- (state 5).
    assert int(path[0]) == 5
    assert score == pytest.approx(np.log(0.2), abs=1e-4)


def test_jit_cache_stability():
    # Two calls with same shapes must not retrace into wrong results.
    params = presets.durbin_cpg8()
    o1 = jnp.asarray(np.tile([1, 2], 20).astype(np.int32))
    o2 = jnp.asarray(np.zeros(40, dtype=np.int32))
    p1, _ = V.viterbi(params, o1)
    p2, _ = V.viterbi(params, o2)
    assert (np.asarray(p1) < 4).mean() > 0.9
    assert (np.asarray(p2) >= 4).mean() > 0.9
