"""Fault injection with REAL in-jit failures.

tests/test_elastic.py injects faults by raising from Python wrappers around
the backend call; these tests instead provoke errors from INSIDE a jitted
computation (a jax.pure_callback that raises during device execution), which
surfaces as jaxlib's XlaRuntimeError — the exact error class fit()'s recovery
policy claims to catch (train/baum_welch.py: "RuntimeError covers jaxlib's
XlaRuntimeError (OOM, preemption, interconnect)").  This closes the r1 gap
where the retry path was only ever exercised against hand-raised Python
exceptions.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu.models import presets
from cpgisland_tpu.ops.forward_backward import SuffStats, batch_stats
from cpgisland_tpu.train import backends, baum_welch
from cpgisland_tpu.train.elastic import ElasticEStep
from cpgisland_tpu.utils import chunking


@functools.cache
def _host_callback_probe() -> str:
    """Probe host-callback support; '' means supported, else the reason.

    Some PJRT plugins (e.g. the axon TPU tunnel) implement no host send/recv
    callbacks at all — the injection mechanism itself cannot run there.  The
    coverage these tests provide (fit()'s recovery against a REAL
    XlaRuntimeError raised from device execution) holds on any backend with
    callback support; CI's CPU platform always has it.  The probe's actual
    exception goes into the skip reason so an unrelated probe failure (jax
    API change, transient backend error) is distinguishable from genuine
    lack of support."""
    try:
        out = jax.jit(
            lambda x: jax.pure_callback(lambda v: v, jax.ShapeDtypeStruct((), jnp.float32), x)
        )(jnp.float32(1.0))
        return "" if float(out) == 1.0 else f"probe returned {out!r}"
    except Exception as e:
        return f"{type(e).__name__}: {e}"


pytestmark = pytest.mark.skipif(
    bool(_host_callback_probe()),
    reason=f"host-callback probe failed: {_host_callback_probe()[:300]}",
)


class InJitFaultBackend(backends.EStepBackend):
    """E-step whose jitted computation fails on device for the first
    ``fail_times`` executions, then succeeds — a deterministic stand-in for
    a transient device fault (preemption, interconnect hiccup)."""

    def __init__(self, fail_times: int):
        self.fail_times = fail_times
        self.executions = 0

        def guard(ll):
            self.executions += 1
            if self.executions <= self.fail_times:
                raise RuntimeError("injected device fault")
            return ll

        @jax.jit
        def estep(params, chunks, lengths):
            st = batch_stats(params, chunks, lengths, mode="rescaled")
            # Thread the loglik through a host callback that raises: the
            # failure happens during device-side execution of the jitted
            # program, not in Python around it.
            poked = jax.pure_callback(
                guard, jax.ShapeDtypeStruct((), st.loglik.dtype), st.loglik
            )
            return SuffStats(
                init=st.init, trans=st.trans, emit=st.emit,
                loglik=poked, n_seqs=st.n_seqs,
            )

        self._estep = estep

    def __call__(self, params, chunks, lengths):
        return self._estep(params, jnp.asarray(chunks), jnp.asarray(lengths))


def _chunked(rng):
    return chunking.frame(rng.integers(0, 4, size=2048).astype(np.uint8), 256)


def test_injit_fault_is_xla_runtime_error(rng):
    """Precondition for everything below: the injected failure really is an
    XlaRuntimeError (RuntimeError subclass) raised at materialization."""
    bad = InJitFaultBackend(fail_times=10)
    ck = _chunked(rng)
    with pytest.raises(RuntimeError, match="injected device fault"):
        st = bad(presets.durbin_cpg8(), ck.chunks, ck.lengths)
        np.asarray(st.loglik)


def test_fit_retries_through_injit_fault(rng):
    """One in-jit failure -> the same-backend retry recovers; training
    completes with no fallback and no recovery record."""
    bad = InJitFaultBackend(fail_times=1)
    res = baum_welch.fit(
        presets.durbin_cpg8(), _chunked(rng), num_iters=2, convergence=0.0,
        backend=bad,
    )
    assert res.iterations == 2
    assert all(np.isfinite(ll) for ll in res.logliks)
    assert res.recoveries == []
    assert bad.executions >= 3  # 1 failed + 2 good iterations


def test_fit_falls_back_after_injit_faults(rng):
    """Two consecutive in-jit failures -> fit switches to the fallback
    backend and records the recovery."""
    bad = InJitFaultBackend(fail_times=10**9)  # never recovers
    res = baum_welch.fit(
        presets.durbin_cpg8(), _chunked(rng), num_iters=2, convergence=0.0,
        backend=bad, fallback_backend=backends.LocalBackend(engine="xla"),
    )
    assert res.iterations == 2
    assert all(np.isfinite(ll) for ll in res.logliks)
    assert len(res.recoveries) == 1
    assert "injected device fault" in res.recoveries[0][1]


def test_fit_raises_after_exhausted_injit_retries(rng):
    bad = InJitFaultBackend(fail_times=10**9)
    with pytest.raises(RuntimeError, match="injected device fault"):
        baum_welch.fit(
            presets.durbin_cpg8(), _chunked(rng), num_iters=1, convergence=0.0,
            backend=bad,
        )


def test_elastic_skips_injit_faulting_slice(rng):
    """ElasticEStep against a backend whose jitted program fails on device
    for its first attempts: the slice retries, then drops under
    on_failure='skip', and the surviving statistics stay usable."""
    ck = _chunked(rng)
    bad = InJitFaultBackend(fail_times=10**9)
    el = ElasticEStep(bad, micro_batches=2, max_retries=1, on_failure="skip")
    el_ck = el.prepare(ck)
    with pytest.raises(RuntimeError, match="all .* micro-batches failed"):
        el(presets.durbin_cpg8(), el_ck.chunks, el_ck.lengths)
    assert len(el.failures) == 2
    assert all("injected device fault" in f.error for f in el.failures)
