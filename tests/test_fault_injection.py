"""Fault injection with REAL in-jit failures.

tests/test_elastic.py injects faults by raising from Python wrappers around
the backend call; these tests instead provoke errors from INSIDE a jitted
computation (a jax.pure_callback that raises during device execution), which
surfaces as jaxlib's XlaRuntimeError — the exact error class fit()'s recovery
policy claims to catch (train/baum_welch.py: "RuntimeError covers jaxlib's
XlaRuntimeError (OOM, preemption, interconnect)").  This closes the r1 gap
where the retry path was only ever exercised against hand-raised Python
exceptions.

The second half drives the SERVING paths the same way: the sharded
decode/posterior programs are wrapped so their outputs flow through a
raising callback, and ``decode_file``/``posterior_file`` must recover
through the resilience dispatch supervisor with bit-identical final
output — both island engines, span-threaded records, prefetch on and off.
"""

import functools
import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu import pipeline, resilience
from cpgisland_tpu.models import presets
from cpgisland_tpu.ops.forward_backward import SuffStats, batch_stats
from cpgisland_tpu.train import backends, baum_welch
from cpgisland_tpu.train.elastic import ElasticEStep
from cpgisland_tpu.utils import chunking


@functools.cache
def _host_callback_probe() -> str:
    """Probe host-callback support; '' means supported, else the reason.

    Some PJRT plugins (e.g. the axon TPU tunnel) implement no host send/recv
    callbacks at all — the injection mechanism itself cannot run there.  The
    coverage these tests provide (fit()'s recovery against a REAL
    XlaRuntimeError raised from device execution) holds on any backend with
    callback support; CI's CPU platform always has it.  The probe's actual
    exception goes into the skip reason so an unrelated probe failure (jax
    API change, transient backend error) is distinguishable from genuine
    lack of support."""
    try:
        out = jax.jit(
            lambda x: jax.pure_callback(lambda v: v, jax.ShapeDtypeStruct((), jnp.float32), x)
        )(jnp.float32(1.0))
        return "" if float(out) == 1.0 else f"probe returned {out!r}"
    except Exception as e:
        return f"{type(e).__name__}: {e}"


pytestmark = pytest.mark.skipif(
    bool(_host_callback_probe()),
    reason=f"host-callback probe failed: {_host_callback_probe()[:300]}",
)


class InJitFaultBackend(backends.EStepBackend):
    """E-step whose jitted computation fails on device for the first
    ``fail_times`` executions, then succeeds — a deterministic stand-in for
    a transient device fault (preemption, interconnect hiccup)."""

    def __init__(self, fail_times: int):
        self.fail_times = fail_times
        self.executions = 0

        def guard(ll):
            self.executions += 1
            if self.executions <= self.fail_times:
                raise RuntimeError("injected device fault")
            return ll

        @jax.jit
        def estep(params, chunks, lengths):
            st = batch_stats(params, chunks, lengths, mode="rescaled")
            # Thread the loglik through a host callback that raises: the
            # failure happens during device-side execution of the jitted
            # program, not in Python around it.
            poked = jax.pure_callback(
                guard, jax.ShapeDtypeStruct((), st.loglik.dtype), st.loglik
            )
            return SuffStats(
                init=st.init, trans=st.trans, emit=st.emit,
                loglik=poked, n_seqs=st.n_seqs,
            )

        self._estep = estep

    def __call__(self, params, chunks, lengths):
        return self._estep(params, jnp.asarray(chunks), jnp.asarray(lengths))


def _chunked(rng):
    return chunking.frame(rng.integers(0, 4, size=2048).astype(np.uint8), 256)


def test_injit_fault_is_xla_runtime_error(rng):
    """Precondition for everything below: the injected failure really is an
    XlaRuntimeError (RuntimeError subclass) raised at materialization."""
    bad = InJitFaultBackend(fail_times=10)
    ck = _chunked(rng)
    with pytest.raises(RuntimeError, match="injected device fault"):
        st = bad(presets.durbin_cpg8(), ck.chunks, ck.lengths)
        np.asarray(st.loglik)


def test_fit_retries_through_injit_fault(rng):
    """One in-jit failure -> the same-backend retry recovers; training
    completes with no fallback and no recovery record."""
    bad = InJitFaultBackend(fail_times=1)
    res = baum_welch.fit(
        presets.durbin_cpg8(), _chunked(rng), num_iters=2, convergence=0.0,
        backend=bad,
    )
    assert res.iterations == 2
    assert all(np.isfinite(ll) for ll in res.logliks)
    assert res.recoveries == []
    assert bad.executions >= 3  # 1 failed + 2 good iterations


def test_fit_falls_back_after_injit_faults(rng):
    """Two consecutive in-jit failures -> fit switches to the fallback
    backend and records the recovery."""
    bad = InJitFaultBackend(fail_times=10**9)  # never recovers
    res = baum_welch.fit(
        presets.durbin_cpg8(), _chunked(rng), num_iters=2, convergence=0.0,
        backend=bad, fallback_backend=backends.LocalBackend(engine="xla"),
    )
    assert res.iterations == 2
    assert all(np.isfinite(ll) for ll in res.logliks)
    assert len(res.recoveries) == 1
    assert "injected device fault" in res.recoveries[0][1]


def test_fit_raises_after_exhausted_injit_retries(rng):
    bad = InJitFaultBackend(fail_times=10**9)
    with pytest.raises(RuntimeError, match="injected device fault"):
        baum_welch.fit(
            presets.durbin_cpg8(), _chunked(rng), num_iters=1, convergence=0.0,
            backend=bad,
        )


@pytest.fixture(autouse=True)
def _fresh_resilience_state():
    """Injected faults feed the global engine breaker; they must not trip
    engines for later tests."""
    resilience.reset()
    yield
    resilience.reset()


def _poke_through_callback(fail_times: int):
    """A device-fault injector for real serving programs.

    ``poke(x)`` runs a SCALAR jitted pure_callback that raises for its
    first ``fail_times`` executions and folds the (zeroed) result back
    into ``x`` — so the fault is a real in-jit failure raised during
    device execution of the record's computation, surfacing as a
    RuntimeError inside the supervised dispatch unit.  The callback
    program is deliberately scalar/single-device: a raising callback
    inside a multi-device gather of the sharded output wedges the other
    seven virtual devices at the collective rendezvous forever (observed:
    XLA:CPU AllReduce participants waiting on the failed rank)."""
    state = {"execs": 0}

    def guard(v):
        state["execs"] += 1
        if state["execs"] <= fail_times:
            raise RuntimeError("injected device fault")
        return v

    @jax.jit
    def gate(v):
        return jax.pure_callback(
            guard, jax.ShapeDtypeStruct((), jnp.float32), v
        )

    def poke(x):
        g = gate(jnp.float32(state["execs"]))
        return x + g.astype(x.dtype) * 0

    return poke, state


def _patch_decode_engines(monkeypatch, poke) -> None:
    """Route every decode program's output (sharded + batched) through the
    raising callback."""
    from cpgisland_tpu.parallel import decode as decode_mod

    real_fn = decode_mod._sharded_fn

    def patched_sharded(mesh, block_size, engine, continuation):
        fn = real_fn(mesh, block_size, engine, continuation)

        def wrapped(params, arr, v, anchor, prev0):
            path, prev_exit = fn(params, arr, v, anchor, prev0)
            return poke(path), prev_exit

        return wrapped

    monkeypatch.setattr(decode_mod, "_sharded_fn", patched_sharded)

    # Patch at the SOURCE module: Session.batch_decode_fn imports the
    # batch entry lazily per call, so this is the one spot every consumer
    # (decode_file with or without an explicit session, the serve broker)
    # reads through.
    from cpgisland_tpu.ops import viterbi_parallel as vp_mod

    real_batch = vp_mod.viterbi_parallel_batch

    def patched_batch(params, chunks, lengths, **kw):
        return poke(real_batch(params, chunks, lengths, **kw))

    monkeypatch.setattr(vp_mod, "viterbi_parallel_batch", patched_batch)


def _patch_posterior_engine(monkeypatch, poke) -> None:
    from cpgisland_tpu.parallel import posterior as posterior_mod

    real_fn = posterior_mod._posterior_fn

    def patched(mesh, block_size, engine, first, want_path, lane_T, t_tile,
                fused=True, one_pass=False):
        fn = real_fn(
            mesh, block_size, engine, first, want_path, lane_T, t_tile, fused,
            one_pass,
        )

        def wrapped(params, arr, lens, mask, enter, exit_, prev):
            conf, path = fn(params, arr, lens, mask, enter, exit_, prev)
            return poke(conf), (poke(path) if path is not None else None)

        return wrapped

    monkeypatch.setattr(posterior_mod, "_posterior_fn", patched)


def _write_fasta(path, rng, n_records=5):
    """Multi-record FASTA spanning both the batched small-record path and
    (with span=2048) the span-threaded per-record path."""
    bases = np.array(list("acgt"))
    with open(path, "w") as f:
        for r in range(n_records):
            f.write(f">rec{r}\n")
            n = 512 + 900 * r
            bg = rng.choice(4, size=n, p=[0.3, 0.2, 0.2, 0.3])
            bg[: n // 4] = rng.choice(4, size=n // 4, p=[0.1, 0.4, 0.4, 0.1])
            s = "".join(bases[bg])
            for i in range(0, len(s), 70):
                f.write(s[i : i + 70] + "\n")
    return str(path)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
@pytest.mark.parametrize("island_engine", ["host", "device"])
@pytest.mark.parametrize("prefetch", [0, 2])
def test_decode_file_recovers_from_injit_fault(
    tmp_path, rng, monkeypatch, island_engine, prefetch
):
    """A real in-jit device fault on the decode path (surfacing as
    XlaRuntimeError at the supervised blocking point — or at the DEFERRED
    column fetch under prefetch, where the serial recompute fallback takes
    over) recovers automatically with bit-identical island output."""
    fa = _write_fasta(tmp_path / "g.fa", rng)
    params = presets.durbin_cpg8()

    def run():
        out = io.StringIO()
        pipeline.decode_file(
            fa, params, islands_out=out, compat=False, span=2048,
            island_engine=island_engine, prefetch=prefetch,
        )
        return out.getvalue()

    clean = run()
    assert clean.count("\n") >= 2
    poke, state = _poke_through_callback(fail_times=1)
    _patch_decode_engines(monkeypatch, poke)
    injected = run()
    assert injected == clean
    assert state["execs"] >= 2  # the fault really fired and was re-run


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
@pytest.mark.parametrize("island_engine", ["host", "device"])
@pytest.mark.parametrize("prefetch", [0, 2])
def test_posterior_file_recovers_from_injit_fault(
    tmp_path, rng, monkeypatch, island_engine, prefetch
):
    fa = _write_fasta(tmp_path / "p.fa", rng)
    params = presets.durbin_cpg8()

    def run():
        out = io.StringIO()
        res = pipeline.posterior_file(
            fa, params, islands_out=out, span=2048,
            island_engine=island_engine, prefetch=prefetch,
        )
        return out.getvalue(), res.mean_island_confidence

    clean_txt, clean_conf = run()
    assert clean_txt.count("\n") >= 2
    poke, state = _poke_through_callback(fail_times=1)
    _patch_posterior_engine(monkeypatch, poke)
    inj_txt, inj_conf = run()
    assert inj_txt == clean_txt
    assert inj_conf == clean_conf
    assert state["execs"] >= 2


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_decode_file_persistent_fault_raises(tmp_path, rng, monkeypatch):
    """A fault that never clears exhausts the bounded retries and
    propagates (no infinite loop, no silent wrong output)."""
    fa = _write_fasta(tmp_path / "g.fa", rng, n_records=2)
    poke, _state = _poke_through_callback(fail_times=10**9)
    _patch_decode_engines(monkeypatch, poke)
    with pytest.raises(RuntimeError, match="injected device fault"):
        pipeline.decode_file(
            fa, presets.durbin_cpg8(), islands_out=io.StringIO(),
            compat=False, span=2048, island_engine="host",
        )


def test_decode_fault_feeds_breaker_and_ladder(tmp_path, rng, monkeypatch):
    """Serving faults are ledgered per attempt AND feed the engine
    breaker: enough consecutive faults trip the engine (engine_degraded),
    and cooldown expiry + a healthy probe restores it (engine_restored) —
    the degradation ladder proven against REAL in-jit faults."""
    from cpgisland_tpu import obs

    t = [0.0]
    br = resilience.EngineBreaker(threshold=2, cooldown_s=30.0,
                                  clock=lambda: t[0])
    resilience.set_breaker(br)
    fa = _write_fasta(tmp_path / "g.fa", rng, n_records=2)
    poke, _state = _poke_through_callback(fail_times=2)
    _patch_decode_engines(monkeypatch, poke)
    with obs.observe() as ob:
        out = io.StringIO()
        pipeline.decode_file(
            fa, presets.durbin_cpg8(), islands_out=out, compat=False,
            span=2048, island_engine="host",
        )
        assert out.getvalue().count("\n") >= 1
    faults = [e for e in ob.events if e["event"] == "dispatch_fault"]
    assert len(faults) >= 2  # every attempt ledgered
    degraded = [e for e in ob.events if e["event"] == "engine_degraded"]
    assert degraded and degraded[0]["engine"] == "decode.xla"
    # Tripped now; after the cooldown the next ROUTING consult admits a
    # half-open probe, and a healthy supervised unit restores the engine.
    assert br.tripped("decode.xla")
    t[0] = 31.0
    assert br.allowed("decode.xla")  # routing's probe admission
    with obs.observe() as ob2:
        sup = resilience.DispatchSupervisor(
            resilience.RetryPolicy(backoff_base_s=0.0), breaker=br
        )
        sup.run(lambda: 1, what="decode.record", engine="decode.xla")
    assert not br.tripped("decode.xla")
    assert any(e["event"] == "engine_restored" for e in ob2.events)


def test_fit_faults_feed_em_breaker(rng):
    """The host-loop recovery records E-step faults/successes under the
    backend's resolved ``em.<engine>`` key, so the train router's
    degradation ladder is actually fed (a trip reroutes the next
    iteration's per-call re-resolution)."""
    events = []
    br = resilience.EngineBreaker(threshold=10, cooldown_s=30.0)
    real_fault, real_ok = br.record_fault, br.record_success
    br.record_fault = lambda k, error=None: (events.append(("fault", k)),
                                             real_fault(k, error=error))[1]
    br.record_success = lambda k: (events.append(("ok", k)), real_ok(k))[1]
    resilience.set_breaker(br)

    class FlakyLocal(backends.LocalBackend):
        def __init__(self):
            super().__init__(engine="xla")
            self.n = 0

        def __call__(self, params, chunks, lengths):
            self.n += 1
            if self.n == 1:
                raise RuntimeError("kernel-shaped fault")
            return super().__call__(params, chunks, lengths)

    res = baum_welch.fit(
        presets.durbin_cpg8(), _chunked(rng), num_iters=1, convergence=0.0,
        backend=FlakyLocal(), fuse=False,
    )
    assert res.iterations == 1
    assert ("fault", "em.xla") in events
    assert ("ok", "em.xla") in events


def test_elastic_skips_injit_faulting_slice(rng):
    """ElasticEStep against a backend whose jitted program fails on device
    for its first attempts: the slice retries, then drops under
    on_failure='skip', and the surviving statistics stay usable."""
    ck = _chunked(rng)
    bad = InJitFaultBackend(fail_times=10**9)
    el = ElasticEStep(bad, micro_batches=2, max_retries=1, on_failure="skip")
    el_ck = el.prepare(ck)
    with pytest.raises(RuntimeError, match="all .* micro-batches failed"):
        el(presets.durbin_cpg8(), el_ck.chunks, el_ck.lengths)
    assert len(el.failures) == 2
    assert all("injected device fault" in f.error for f in el.failures)
