"""Native C++ codec vs. the NumPy reference implementation.

The native library is a throughput optimization with identical semantics; if
the toolchain can't build it these tests skip (the NumPy path is then the one
exercised everywhere else).
"""

import os

import numpy as np
import pytest

from cpgisland_tpu.utils import codec, native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no C++ toolchain?)"
)


def _random_fasta_bytes(rng, n=100_000):
    """Adversarial byte soup: bases, headers, mid-line '>', split newlines."""
    pieces = []
    while sum(len(p) for p in pieces) < n:
        kind = rng.integers(0, 5)
        if kind == 0:
            pieces.append(rng.choice(list(b"ACGTacgt"), size=rng.integers(1, 200)).tobytes())
        elif kind == 1:
            pieces.append(b">chr" + bytes(rng.integers(48, 123, size=rng.integers(0, 30)).tolist()) + b"\n")
        elif kind == 2:
            pieces.append(b"\n" * rng.integers(1, 3))
        elif kind == 3:
            pieces.append(bytes(rng.integers(0, 256, size=rng.integers(1, 50)).tolist()))
        else:
            pieces.append(b"ACG>TAC")  # mid-line '>' must NOT open a header
    return b"".join(pieces)


def test_encode_parity(rng):
    data = _random_fasta_bytes(rng)
    got = native.encode(data)
    want = codec.encode_bytes(data)
    np.testing.assert_array_equal(got, want)


def test_fasta_encode_parity_across_block_splits(rng):
    data = _random_fasta_bytes(rng, n=50_000)
    want = codec.encode_bytes(codec.strip_fasta_headers(data))
    for block in (1, 7, 4096, len(data)):
        enc = native.FastaEncoder()
        parts = [enc.feed(data[i : i + block]) for i in range(0, len(data), block)]
        got = np.concatenate(parts) if parts else np.zeros(0, np.uint8)
        np.testing.assert_array_equal(got, want, err_msg=f"block={block}")


def test_file_streaming_uses_native_and_matches(tmp_path, rng):
    data = _random_fasta_bytes(rng, n=200_000)
    p = tmp_path / "g.fa"
    p.write_bytes(data)
    via_file = codec.encode_file(str(p), skip_headers=True)
    want = codec.encode_bytes(codec.strip_fasta_headers(data))
    np.testing.assert_array_equal(via_file, want)
    # compat path too
    via_file_c = codec.encode_file(str(p), skip_headers=False)
    np.testing.assert_array_equal(via_file_c, codec.encode_bytes(data))


def test_native_can_be_disabled(tmp_path, monkeypatch, rng):
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)
    assert not native.available()
    data = b">h\nACGT\n"
    p = tmp_path / "g.fa"
    p.write_bytes(data)
    np.testing.assert_array_equal(
        codec.encode_file(str(p), skip_headers=True), [0, 1, 2, 3]
    )
