"""Native C++ codec vs. the NumPy reference implementation.

The native library is a throughput optimization with identical semantics; if
the toolchain can't build it these tests skip (the NumPy path is then the one
exercised everywhere else).
"""

import os

import numpy as np
import pytest

from cpgisland_tpu.utils import codec, native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no C++ toolchain?)"
)


def _random_fasta_bytes(rng, n=100_000):
    """Adversarial byte soup: bases, headers, mid-line '>', split newlines."""
    pieces = []
    while sum(len(p) for p in pieces) < n:
        kind = rng.integers(0, 5)
        if kind == 0:
            pieces.append(rng.choice(list(b"ACGTacgt"), size=rng.integers(1, 200)).tobytes())
        elif kind == 1:
            pieces.append(b">chr" + bytes(rng.integers(48, 123, size=rng.integers(0, 30)).tolist()) + b"\n")
        elif kind == 2:
            pieces.append(b"\n" * rng.integers(1, 3))
        elif kind == 3:
            pieces.append(bytes(rng.integers(0, 256, size=rng.integers(1, 50)).tolist()))
        else:
            pieces.append(b"ACG>TAC")  # mid-line '>' must NOT open a header
    return b"".join(pieces)


def test_encode_parity(rng):
    data = _random_fasta_bytes(rng)
    got = native.encode(data)
    want = codec.encode_bytes(data)
    np.testing.assert_array_equal(got, want)


def test_fasta_encode_parity_across_block_splits(rng):
    data = _random_fasta_bytes(rng, n=50_000)
    want = codec.encode_bytes(codec.strip_fasta_headers(data))
    for block in (1, 7, 4096, len(data)):
        enc = native.FastaEncoder()
        parts = [enc.feed(data[i : i + block]) for i in range(0, len(data), block)]
        got = np.concatenate(parts) if parts else np.zeros(0, np.uint8)
        np.testing.assert_array_equal(got, want, err_msg=f"block={block}")


def test_file_streaming_uses_native_and_matches(tmp_path, rng):
    data = _random_fasta_bytes(rng, n=200_000)
    p = tmp_path / "g.fa"
    p.write_bytes(data)
    via_file = codec.encode_file(str(p), skip_headers=True)
    want = codec.encode_bytes(codec.strip_fasta_headers(data))
    np.testing.assert_array_equal(via_file, want)
    # compat path too
    via_file_c = codec.encode_file(str(p), skip_headers=False)
    np.testing.assert_array_equal(via_file_c, codec.encode_bytes(data))


def test_native_can_be_disabled(tmp_path, monkeypatch, rng):
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)
    assert not native.available()
    data = b">h\nACGT\n"
    p = tmp_path / "g.fa"
    p.write_bytes(data)
    np.testing.assert_array_equal(
        codec.encode_file(str(p), skip_headers=True), [0, 1, 2, 3]
    )


# ---------------------------------------------------------------------------
# Multithreaded whole-buffer encode (cpg_count_segments / cpg_encode_segments)


def _fasta_oracle(data: bytes) -> np.ndarray:
    return codec.encode_bytes(codec.strip_fasta_headers(data))


def _random_fasta(rng, n_records=5, seq_len=50000) -> bytes:
    parts = []
    for i in range(n_records):
        parts.append(f">chr{i} some description acgt\n".encode())
        seq = rng.choice(list(b"ACGTacgtNnX\n"), size=seq_len).astype(np.uint8).tobytes()
        parts.append(seq + b"\n")
    return b"".join(parts)


@pytest.mark.skipif(not native.available(), reason="native library unavailable")
@pytest.mark.parametrize("threads", [1, 3, 0])
def test_encode_mt_raw_parity(rng, threads):
    data = rng.choice(list(b"ACGTacgtNnX>\n \t0"), size=300001).astype(np.uint8).tobytes()
    got = native.encode_mt(data, fasta=False, threads=threads)
    np.testing.assert_array_equal(got, codec.encode_bytes(data))


@pytest.mark.skipif(not native.available(), reason="native library unavailable")
@pytest.mark.parametrize("threads", [1, 3, 0])
def test_encode_mt_fasta_parity(rng, threads):
    data = _random_fasta(rng)
    got = native.encode_mt(data, fasta=True, threads=threads)
    np.testing.assert_array_equal(got, _fasta_oracle(data))


@pytest.mark.skipif(not native.available(), reason="native library unavailable")
def test_encode_mt_edge_cases():
    assert native.encode_mt(b"", fasta=True).size == 0
    assert native.encode_mt(b">only a header no newline", fasta=True).size == 0
    np.testing.assert_array_equal(
        native.encode_mt(b">h\nACGT", fasta=True), np.array([0, 1, 2, 3], np.uint8)
    )
    # trailing data without newline; header token mid-sequence is not a header
    data = b">h\nAC>GT\nacg"
    np.testing.assert_array_equal(native.encode_mt(data, fasta=True), _fasta_oracle(data))


@pytest.mark.skipif(not native.available(), reason="native library unavailable")
def test_encode_mt_header_spans_segment_boundary(rng):
    # one huge header line (> typical segment size at threads=8) must strip fully
    data = b">" + bytes(rng.choice(list(b"abcdefgh ACGT"), size=200000).astype(np.uint8)) + b"\nACGTN\n"
    got = native.encode_mt(data, fasta=True, threads=8)
    np.testing.assert_array_equal(got, _fasta_oracle(data))


@pytest.mark.skipif(not native.available(), reason="native library unavailable")
def test_encode_file_mt_path(tmp_path, rng, monkeypatch):
    data = _random_fasta(rng, n_records=3, seq_len=40000)
    p = tmp_path / "g.fa"
    p.write_bytes(data)
    monkeypatch.setattr(codec, "_MT_THRESHOLD", 1024)  # force the MT path
    got = codec.encode_file(str(p), skip_headers=True)
    np.testing.assert_array_equal(got, _fasta_oracle(data))
    got_compat = codec.encode_file(str(p), skip_headers=False)
    np.testing.assert_array_equal(got_compat, codec.encode_bytes(data))


@pytest.mark.skipif(not native.available(), reason="native library unavailable")
def test_encode_mt_multi_segment_parity(rng):
    """Buffers past the 4 MiB/thread floor so multiple segments ACTUALLY run:
    exercises segment offsets, boundary-adjacent skips, and concurrent writes
    (the single-threaded clamp hid a segment-boundary write race once)."""
    # ~16 MiB with non-bases adjacent to segment boundaries
    data = (b"ACGT" * 1000 + b"NN\n") * 4200
    oracle = codec.encode_bytes(data)
    for threads in (2, 4, 8):
        got = native.encode_mt(data, fasta=False, threads=threads)
        np.testing.assert_array_equal(got, oracle)
    # FASTA flavour with headers sprinkled through all segments
    rec = b">r fasta header line\n" + (b"acgtNRYK" * 1000 + b"\n") * 250
    fdata = rec * 8  # ~16 MiB
    foracle = codec.encode_bytes(codec.strip_fasta_headers(fdata))
    for threads in (2, 4, 8):
        got = native.encode_mt(fdata, fasta=True, threads=threads)
        np.testing.assert_array_equal(got, foracle)


@pytest.mark.skipif(not native.available(), reason="native library unavailable")
def test_encode_mt_giant_header_spans_segments(rng):
    """A >4 MiB header line must strip fully even when it spans the nominal
    segment boundaries of a genuinely multi-threaded run."""
    header = b">" + bytes(rng.choice(list(b"acgt ACGT_"), size=6 << 20).astype(np.uint8)) + b"\n"
    data = header + (b"ACGTacgt" * 1000 + b"\n") * 1200  # ~15 MiB total
    got = native.encode_mt(data, fasta=True, threads=8)
    np.testing.assert_array_equal(got, codec.encode_bytes(codec.strip_fasta_headers(data)))
