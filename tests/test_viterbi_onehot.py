"""The reduced one-hot Viterbi engine vs the generic engines (exactness).

The "onehot" engine (ops.viterbi_onehot) collapses one-hot-emission models
(the flagship Durbin 8-state preset, CpGIslandFinder.java:166-173) to a
2-state conditional chain.  Contract pinned here: paths identical to the
generic engines on tie-free inputs, achieved scores equal to f32-rounding
tolerance (the engines' per-block normalizers can differ in the last ulp —
see the module docstring), PAD handling (mid-sequence and tail) exact, and
the sharded / span / batch drivers agree engine-for-engine.

On non-TPU backends the engine runs its XLA lowering; the TPU suite run
(CPGISLAND_TEST_PLATFORM=axon) exercises the Pallas kernels against these
same tests — both lowerings implement identical arithmetic.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import HmmParams, sample_sequence
from cpgisland_tpu.ops import viterbi_onehot as OH
from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel, viterbi_parallel_batch
from cpgisland_tpu.parallel import decode as pdec


def _onehot_model(rng, S=4, perm=None):
    """Random one-hot-emission model: K = 2*S states, state k emits exactly
    one symbol; ``perm`` scrambles which states group under which symbol
    (non-contiguous groups must work too)."""
    K = 2 * S
    if perm is None:
        perm = rng.permutation(K)
    sym_of_state = np.empty(K, dtype=np.int64)
    for s in range(S):
        sym_of_state[perm[2 * s]] = s
        sym_of_state[perm[2 * s + 1]] = s
    pi = rng.dirichlet(np.ones(K))
    A = rng.dirichlet(np.ones(K), size=K)
    B = np.zeros((K, S))
    B[np.arange(K), sym_of_state] = 1.0
    # iid logit perturbation -> argmax ties have probability ~0.
    A = A * np.exp(rng.normal(scale=1e-3, size=A.shape))
    A = A / A.sum(axis=1, keepdims=True)
    return HmmParams.from_probs(pi, A, B)


def _path_score(params, obs, path):
    lp = np.asarray(params.log_pi)
    lA = np.asarray(params.log_A)
    lB = np.asarray(params.log_B)
    S = lB.shape[1]
    first = next((i for i, o in enumerate(obs) if o < S), None)
    s = lp[path[0]] + (lB[path[0], obs[0]] if obs[0] < S else 0.0)
    for t in range(1, len(obs)):
        if obs[t] >= S:  # PAD: identity step
            assert path[t] == path[t - 1]
            continue
        s += lA[path[t - 1], path[t]] + lB[path[t], obs[t]]
    return s


def test_supports():
    assert OH.supports(presets.durbin_cpg8())
    rng = np.random.default_rng(0)
    dense = HmmParams.from_probs(
        rng.dirichlet(np.ones(4)),
        rng.dirichlet(np.ones(4), size=4),
        rng.dirichlet(np.ones(4), size=4),
    )
    assert not OH.supports(dense)
    # One-hot but 4 states on one symbol / 0 on another: unequal groups.
    B = np.zeros((4, 2))
    B[:, 0] = 1.0
    skew = HmmParams.from_probs(
        rng.dirichlet(np.ones(4)), rng.dirichlet(np.ones(4), size=4), B
    )
    assert not OH.supports(skew)


def test_groups_table_matches_support(rng):
    params = _onehot_model(rng)
    gt = np.asarray(OH._groups(params))
    B = np.asarray(params.B)
    for s in range(params.n_symbols):
        members = np.nonzero(B[:, s] > 0)[0]
        assert gt[s].tolist() == sorted(members.tolist())


@pytest.mark.parametrize("T,block", [(5, 4), (64, 8), (257, 32), (2000, 256), (5000, 512)])
def test_matches_generic_engine(rng, T, block):
    params = _onehot_model(rng)
    obs = jnp.asarray(rng.integers(0, 4, size=T))
    p_x, s_x = viterbi_parallel(params, obs, block_size=block, engine="xla")
    p_o, s_o = viterbi_parallel(params, obs, block_size=block, engine="onehot")
    assert np.array_equal(np.asarray(p_x), np.asarray(p_o))
    assert float(s_o) == pytest.approx(float(s_x), rel=1e-5, abs=2e-2)


def test_flagship_model_long(rng):
    params = presets.durbin_cpg8()
    _, obs = sample_sequence(params, jax.random.PRNGKey(3), 30000)
    p_x, s_x = viterbi_parallel(params, obs, block_size=1024, engine="xla")
    p_o, s_o = viterbi_parallel(params, obs, block_size=1024, engine="onehot")
    assert np.array_equal(np.asarray(p_x), np.asarray(p_o))
    assert float(s_o) == pytest.approx(float(s_x), rel=1e-5, abs=2e-2)


def test_tail_and_mid_pads(rng):
    """PAD symbols are identity steps anywhere after position 0."""
    params = _onehot_model(rng)
    obs = np.asarray(rng.integers(0, 4, size=600), dtype=np.int32)
    obs[200:230] = 4  # mid-sequence PAD run
    obs[580:] = 4  # tail PADs
    p_x, s_x = viterbi_parallel(params, jnp.asarray(obs), block_size=64, engine="xla")
    p_o, s_o = viterbi_parallel(params, jnp.asarray(obs), block_size=64, engine="onehot")
    assert np.array_equal(np.asarray(p_x), np.asarray(p_o))
    assert float(s_o) == pytest.approx(float(s_x), rel=1e-5, abs=2e-2)
    # Both achieve the score they report (identity steps hold state).
    got = _path_score(params, obs, np.asarray(p_o))
    assert got == pytest.approx(float(s_x), rel=1e-5, abs=2e-2)


def test_pad_run_across_block_boundary(rng):
    """A PAD run spanning a block boundary exercises the cross-block
    forward-fill seed (the [nb]-level cummax in _pair_stream)."""
    params = _onehot_model(rng)
    obs = np.asarray(rng.integers(0, 4, size=512), dtype=np.int32)
    obs[120:200] = 4  # covers the 128-boundary for block=64
    p_x = viterbi_parallel(params, jnp.asarray(obs), block_size=64, engine="xla",
                           return_score=False)
    p_o = viterbi_parallel(params, jnp.asarray(obs), block_size=64, engine="onehot",
                           return_score=False)
    assert np.array_equal(np.asarray(p_x), np.asarray(p_o))


def test_batch_parity(rng):
    params = _onehot_model(rng)
    N, T = 5, 700
    chunks = rng.integers(0, 4, size=(N, T)).astype(np.int32)
    lengths = np.asarray([700, 650, 1, 300, 700], dtype=np.int32)
    p_x = viterbi_parallel_batch(
        params, jnp.asarray(chunks), jnp.asarray(lengths), block_size=128,
        return_score=False, engine="xla",
    )
    p_o = viterbi_parallel_batch(
        params, jnp.asarray(chunks), jnp.asarray(lengths), block_size=128,
        return_score=False, engine="onehot",
    )
    for i in range(N):
        L = int(lengths[i])
        assert np.array_equal(np.asarray(p_x)[i, :L], np.asarray(p_o)[i, :L])


def test_sharded_parity(rng):
    """Sequence-parallel decode over the 8-device mesh, engine-for-engine."""
    params = _onehot_model(rng)
    obs = rng.integers(0, 4, size=8 * 64 * 3 + 17).astype(np.uint8)
    p_x = pdec.viterbi_sharded(params, obs, block_size=64, engine="xla")
    p_o = pdec.viterbi_sharded(params, obs, block_size=64, engine="onehot")
    assert np.array_equal(np.asarray(p_x), np.asarray(p_o))


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_span_parity(rng):
    """Span-threaded decode (multiple spans, boundary messages) matches the
    one-shot decode with the onehot engine on both sides."""
    params = _onehot_model(rng)
    T = 8 * 64 * 4 + 9
    obs = rng.integers(0, 4, size=T).astype(np.uint8)
    one = pdec.viterbi_sharded(params, obs, block_size=64, engine="onehot")
    spans = pdec.viterbi_sharded_spans(
        params, obs, span=8 * 64 * 2, block_size=64, engine="onehot"
    )
    stitched = np.concatenate([np.asarray(p) for p in spans])
    assert np.array_equal(np.asarray(one), stitched)
    # And against the generic engine end to end.
    spans_x = pdec.viterbi_sharded_spans(
        params, obs, span=8 * 64 * 2, block_size=64, engine="xla"
    )
    assert np.array_equal(stitched, np.concatenate([np.asarray(p) for p in spans_x]))


def test_engine_for_record_demotes_pad_first():
    params = presets.durbin_cpg8()
    obs_bad = np.asarray([7, 0, 1], dtype=np.uint8)
    obs_ok = np.asarray([0, 7, 1], dtype=np.uint8)
    # Demotion honors the dense engines' own eligibility (Pallas: TPU-only).
    dense = "pallas" if jax.default_backend() == "tpu" else "xla"
    assert pdec._engine_for_record("onehot", obs_bad, params) == dense
    assert pdec._engine_for_record("onehot", obs_ok, params) == "onehot"
    assert pdec._engine_for_record("onehot", obs_bad[:0], params) == dense
    assert pdec._engine_for_record("xla", obs_bad, params) == "xla"


def test_resolve_engine_validation():
    rng = np.random.default_rng(1)
    dense = HmmParams.from_probs(
        rng.dirichlet(np.ones(4)),
        rng.dirichlet(np.ones(4), size=4),
        rng.dirichlet(np.ones(4), size=4),
    )
    with pytest.raises(ValueError, match="onehot"):
        pdec.resolve_engine("onehot", dense)
    # 'auto' lands on onehot exactly when the Pallas kernels are available.
    expected = "onehot" if jax.default_backend() == "tpu" else "xla"
    assert pdec.resolve_engine("auto", presets.durbin_cpg8()) == expected


def test_prev0_required():
    params = presets.durbin_cpg8()
    steps2 = jnp.zeros((8, 1), jnp.int32)
    with pytest.raises(ValueError, match="prev0"):
        OH.pass_products(params, steps2, None)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_batch_flat_parity(rng):
    """decode_batch_flat (reset-step concatenation) vs per-record decode:
    paths identical on a tie-free model, ragged lengths, mid-record PADs,
    record boundaries off block boundaries (T=700, bk=128)."""
    params = _onehot_model(rng)
    N, T = 5, 700
    chunks = rng.integers(0, 4, size=(N, T)).astype(np.int32)
    chunks[2, 300:320] = 7  # mid-record PAD run (carried states)
    lengths = np.asarray([700, 650, 700, 2, 700], dtype=np.int32)
    flat = OH.decode_batch_flat(
        params, jnp.asarray(chunks), jnp.asarray(lengths), block_size=128
    )
    for i in range(N):
        L = int(lengths[i])
        ref = viterbi_parallel(
            params,
            jnp.asarray(np.where(np.arange(T) >= L, 4, chunks[i])),
            block_size=128, return_score=False, engine="onehot",
        )
        assert np.array_equal(np.asarray(flat)[i, :L], np.asarray(ref)[:L]), i


def test_batch_flat_is_the_batch_api_route(rng):
    """viterbi_parallel_batch(engine='onehot', return_score=False) routes
    through the flat path and matches the vmap route record-for-record."""
    params = _onehot_model(rng)
    N, T = 4, 520
    chunks = rng.integers(0, 4, size=(N, T)).astype(np.int32)
    lengths = np.asarray([520, 300, 1, 520], dtype=np.int32)
    got = viterbi_parallel_batch(
        params, jnp.asarray(chunks), jnp.asarray(lengths), block_size=128,
        return_score=False, engine="onehot",
    )
    want = viterbi_parallel_batch(
        params, jnp.asarray(chunks), jnp.asarray(lengths), block_size=128,
        return_score=False, engine="xla",
    )
    for i in range(N):
        L = int(lengths[i])
        assert np.array_equal(np.asarray(got)[i, :L], np.asarray(want)[i, :L]), i


def test_batch_flat_block_aligned_boundaries(rng):
    """Record boundaries exactly ON block boundaries (T a multiple of bk):
    the reset step is then the LAST step of a block — the stitching case
    the off-boundary test cannot reach."""
    params = _onehot_model(rng)
    N, T, bk = 4, 512, 128  # 512 = 4 blocks exactly
    chunks = rng.integers(0, 4, size=(N, T)).astype(np.int32)
    lengths = np.full(N, T, dtype=np.int32)
    flat = OH.decode_batch_flat(
        params, jnp.asarray(chunks), jnp.asarray(lengths), block_size=bk
    )
    for i in range(N):
        ref = viterbi_parallel(
            params, jnp.asarray(chunks[i]), block_size=bk,
            return_score=False, engine="onehot",
        )
        assert np.array_equal(np.asarray(flat)[i], np.asarray(ref)), i


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_batch_flat_fuzz_geometries(rng):
    """Randomized geometries / raggedness: every record's path must equal
    its standalone decode (achieved-score equality would also hold, but the
    model is tie-free so exact path equality is the stronger check).

    CPU-only: each random geometry is a fresh compile, which costs ~15 min
    of remote-compile round-trips on the relayed chip while exercising only
    the shared stream-assembly logic — the chip run certifies the kernels
    through the deterministic-geometry tests above (all green on TPU,
    2026-08-01)."""
    if jax.default_backend() == "tpu":
        pytest.skip("compile-diversity fuzz is CPU-suite coverage")
    params = _onehot_model(rng)
    for trial in range(6):
        N = int(rng.integers(1, 7))
        T = int(rng.integers(2, 900))
        bk = int(2 ** rng.integers(3, 8))
        chunks = rng.integers(0, 4, size=(N, T)).astype(np.int32)
        lengths = rng.integers(1, T + 1, size=N).astype(np.int32)
        flat = OH.decode_batch_flat(
            params, jnp.asarray(chunks), jnp.asarray(lengths), block_size=bk
        )
        for i in range(N):
            L = int(lengths[i])
            masked = np.where(np.arange(T) >= L, 4, chunks[i])
            ref = viterbi_parallel(
                params, jnp.asarray(masked), block_size=bk,
                return_score=False, engine="onehot",
            )
            assert np.array_equal(
                np.asarray(flat)[i, :L], np.asarray(ref)[:L]
            ), (trial, i, N, T, bk)
