"""Multi-model kernel occupancy tests (r12): N members' reduced chains in
ONE stacked launch set.

The acceptance surface of ROADMAP item 2's stacking half:

- stacked-vs-sequential BIT-IDENTITY per member — decode paths (+scores),
  posterior conf tracks + MPM paths, compare loglik/calls/winner, and EM
  sufficient statistics — for 2/3/5-member sets including the order-2
  dinucleotide pair-lift and random one-hot-partitioned families;
- mixed eligible+dense member sets stack PARTIALLY (dense members stay on
  the sequential arm, results unchanged);
- N=1 degenerates exactly to the single-model path;
- the shared per-order stream placement (encode/pad/place ONCE, zero
  duplicate uploads and zero prepared-cache re-preps on later members —
  ledger-asserted);
- the K<=8 envelope lift: the 32-state dinuc member trains through the
  reduced stats path, dense-twin parity pinned;
- serve: compare flushes and mixed-model decode flushes through the
  stacked dispatch (runs under the session-wide LockTracker when
  CPGISLAND_TRACKSYNC=1, like the rest of the suite);
- graftcost: a planted DE-stacked program (per-member sequential scans)
  must fail the pass pin naming the regrown passes.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from cpgisland_tpu import family
from cpgisland_tpu.models import presets
from cpgisland_tpu.ops import fb_pallas
from cpgisland_tpu.ops import viterbi_onehot as vo
from cpgisland_tpu.parallel import posterior as par_post


def _rand_member(i: int, K: int = 8, S: int = 4):
    return presets.random_hmm(jax.random.PRNGKey(i), K, S, partition=2)


def _cast(n: int):
    """n same-alphabet reduced members: flagship + random g2 families."""
    return tuple(
        [presets.durbin_cpg8()] + [_rand_member(i) for i in range(1, n)]
    )


def _suffstats_equal(a, b):
    for f in ("init", "trans", "emit", "loglik", "n_seqs"):
        if not np.array_equal(np.asarray(getattr(a, f)), np.asarray(getattr(b, f))):
            return False
    return True


# ---------------------------------------------------------------------------
# kernel-level bit-identity (both lowerings: XLA twins here, kernels on TPU)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
@pytest.mark.parametrize("n_members", [1, 2, 3, 5])
def test_stacked_decode_bit_identity(n_members):
    members = _cast(n_members)
    rng = np.random.default_rng(7)
    N, T = 5, 700
    chunks = jnp.asarray(rng.integers(0, 4, size=(N, T)).astype(np.int32))
    lengths = jnp.asarray(np.array([T, 650, T, 20, T], np.int32))
    paths, scores = vo.decode_batch_flat_stacked(
        members, chunks, lengths, block_size=256, return_score=True
    )
    for m, p in enumerate(members):
        rp, rs = vo.decode_batch_flat(
            p, chunks, lengths, block_size=256, return_score=True
        )
        np.testing.assert_array_equal(np.asarray(paths[m]), np.asarray(rp))
        np.testing.assert_array_equal(np.asarray(scores[m]), np.asarray(rs))


@pytest.mark.parametrize(
    "n_members", [2, pytest.param(3, marks=pytest.mark.slow)]
)
@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
@pytest.mark.parametrize("want_path", [False, True])
def test_stacked_posterior_bit_identity(n_members, want_path):
    members = _cast(n_members)
    rng = np.random.default_rng(5)
    obs = rng.integers(0, 4, size=9000).astype(np.uint8)
    isl = [(0, 1, 2, 3)] * n_members
    confs, paths = par_post.posterior_sharded_stacked(
        members, obs, isl, want_path=want_path, pad_to=1 << 14
    )
    for m, p in enumerate(members):
        c, pa = par_post.posterior_sharded(
            p, obs, isl[m], engine="onehot", want_path=want_path,
            pad_to=1 << 14,
        )
        np.testing.assert_array_equal(confs[m], np.asarray(c))
        if want_path:
            np.testing.assert_array_equal(paths[m], np.asarray(pa))


@pytest.mark.parametrize("fused", [True, False])
def test_stacked_em_stats_bit_identity(fused):
    members = _cast(3)
    rng = np.random.default_rng(3)
    n, T = 8, 1024
    chunks = jnp.asarray(rng.integers(0, 4, size=(n, T)).astype(np.uint8))
    lengths = jnp.asarray(np.array([T] * 6 + [300, 0], np.int32))
    st = fb_pallas.batch_stats_pallas_stacked(
        members, chunks, lengths, fused=fused
    )
    for m, p in enumerate(members):
        ref = fb_pallas.batch_stats_pallas(
            p, chunks, lengths, onehot=True, fused=fused
        )
        assert _suffstats_equal(st[m], ref), m


@pytest.mark.slow  # K=32 compiles; the class is also pinned by the dinuc parity test
def test_stacked_em_pair_alphabet_members():
    """Order-2 (16-symbol) stacked EM: the dinuc pair-lift class — two
    random 32-state pair-alphabet members through the stacked stats path."""
    members = (
        _rand_member(11, 32, 16),
        _rand_member(12, 32, 16),
    )
    rng = np.random.default_rng(13)
    n, T = 8, 512
    chunks = jnp.asarray(rng.integers(0, 16, size=(n, T)).astype(np.uint8))
    lengths = jnp.asarray(np.full(n, T, np.int32))
    st = fb_pallas.batch_stats_pallas_stacked(members, chunks, lengths)
    for m, p in enumerate(members):
        ref = fb_pallas.batch_stats_pallas(p, chunks, lengths, onehot=True)
        assert _suffstats_equal(st[m], ref), m


def test_family_estep_and_lockstep_fit():
    from cpgisland_tpu.train import backends
    from cpgisland_tpu.train.baum_welch import mstep

    members = _cast(2)
    rng = np.random.default_rng(3)
    chunks = rng.integers(0, 4, size=(8, 512)).astype(np.uint8)
    lengths = np.full(8, 512, np.int32)
    out, hist = backends.fit_family(list(members), chunks, lengths, n_iter=3)
    assert hist.shape == (3, 2)
    lb = backends.LocalBackend(mode="rescaled", engine="onehot")
    for m, p in enumerate(members):
        q = p.astype(jnp.float32)
        for _ in range(3):
            q = mstep(q, lb(q, chunks, lengths))
        np.testing.assert_array_equal(
            np.asarray(out[m].log_A), np.asarray(q.log_A)
        )
        np.testing.assert_array_equal(
            np.asarray(out[m].log_B), np.asarray(q.log_B)
        )


def test_family_estep_rejects_ineligible_members():
    from cpgisland_tpu.train.backends import FamilyEStep

    estep = FamilyEStep()
    with pytest.raises(ValueError, match="reduced-stats-eligible"):
        estep.validate((presets.durbin_cpg8(), presets.two_state_cpg()))
    with pytest.raises(ValueError, match="share one alphabet"):
        estep.validate((presets.durbin_cpg8(), presets.dinuc_cpg()))


# ---------------------------------------------------------------------------
# compare workload


def _member_objs(n):
    ms = [family.Member("durbin8", presets.durbin_cpg8(), tuple(range(4)), 1)]
    for i in range(1, n):
        ms.append(family.Member(f"rand{i}", _rand_member(i), (0, 2), 1))
    return ms


@pytest.mark.parametrize(
    "n_members", [2, 3, pytest.param(5, marks=pytest.mark.slow)]
)
@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_compare_stacked_vs_sequential(n_members):
    members = _member_objs(n_members)
    rng = np.random.default_rng(11)
    obs = rng.integers(0, 4, size=9000).astype(np.uint8)
    rc_s = family.compare_record(members, obs, engine="onehot", stacked=True)
    rc_q = family.compare_record(members, obs, engine="onehot", stacked=False)
    for a, b in zip(rc_s.members, rc_q.members):
        assert a.loglik == b.loglik and a.log_odds == b.log_odds, a.name
        np.testing.assert_array_equal(a.conf, b.conf)
        np.testing.assert_array_equal(a.calls.beg, b.calls.beg)
        np.testing.assert_array_equal(a.calls.end, b.calls.end)
    np.testing.assert_array_equal(rc_s.winner, rc_q.winner)
    np.testing.assert_array_equal(
        rc_s.winner_calls.beg, rc_q.winner_calls.beg
    )


@pytest.mark.slow  # K=32 pair-alphabet compiles dominate; ci_checks runs it
def test_compare_dinuc_pair_lift_stacked():
    """Order-2 group: dinuc + a random 32-state pair member + null16 — the
    K<=8 lift lets the pair alphabet stack (posterior resolver admits
    K=32 'onehot' since fb_onehot.ONEHOT_MAX_STATES)."""
    members = [
        family.builtin_member("dinuc_cpg"),
        family.Member("rand16", _rand_member(4, 32, 16), (0, 5), 2),
        family.builtin_member("null16"),
    ]
    rng = np.random.default_rng(17)
    obs = rng.integers(0, 4, size=8000).astype(np.uint8)
    rc_s = family.compare_record(members, obs, engine="onehot", stacked=True)
    rc_q = family.compare_record(members, obs, engine="onehot", stacked=False)
    for a, b in zip(rc_s.members, rc_q.members):
        assert a.loglik == b.loglik, a.name
        np.testing.assert_array_equal(a.conf, b.conf)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_compare_mixed_partial_stacking():
    """Eligible members stack; dense members ride the sequential arm —
    per-member engine choice through per-member sessions, results
    unchanged either way."""
    from cpgisland_tpu.serve.session import Session

    m_list = _member_objs(2) + [
        family.builtin_member("two_state"),
        family.builtin_member("null"),
    ]
    sessions = {
        "durbin8": Session(
            m_list[0].params, engine="onehot", name="s0", private_breaker=True
        ),
        "rand1": Session(
            m_list[1].params, engine="onehot", name="s1", private_breaker=True
        ),
        "two_state": Session(
            m_list[2].params, engine="auto", name="s2", private_breaker=True
        ),
    }
    # The grouping itself: only the two onehot-resolved members group.
    from cpgisland_tpu.family import stacked as stacked_mod

    groups = stacked_mod.stack_groups(
        m_list, ["onehot", "onehot", "xla", None]
    )
    assert groups == {1: [0, 1]}
    rng = np.random.default_rng(19)
    obs = rng.integers(0, 4, size=9000).astype(np.uint8)
    rc_s = family.compare_record(m_list, obs, sessions=sessions, stacked=True)
    rc_q = family.compare_record(m_list, obs, sessions=sessions, stacked=False)
    for a, b in zip(rc_s.members, rc_q.members):
        assert a.loglik == b.loglik, a.name
        np.testing.assert_array_equal(a.conf, b.conf)


def test_stack_groups_singleton_not_grouped():
    from cpgisland_tpu.family import stacked as stacked_mod

    m_list = _member_objs(1)
    assert stacked_mod.stack_groups(m_list, ["onehot"]) == {}
    assert stacked_mod.stack_groups(m_list, ["onehot"], enabled=False) == {}


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_compare_shared_placement_zero_duplicate_uploads():
    """Satellite: each order's stream is encoded/padded AND device-placed
    ONCE — the second same-order member adds ZERO upload bytes and ZERO
    prepared-cache misses (the per-member placement half left open in
    PR 10's hardening notes)."""
    from cpgisland_tpu import obs as obs_mod
    from cpgisland_tpu.ops import prepared as prep_mod

    rng = np.random.default_rng(23)
    obs = rng.integers(0, 4, size=9000).astype(np.uint8)
    one = _member_objs(1)
    two = _member_objs(2)

    def upload_bytes(members):
        # Fresh jit caches don't matter for upload accounting (placement
        # goes through device_put / note_upload either way), but warm the
        # programs first so compile-time placements don't differ.
        family.compare_record(members, obs, engine="onehot")
        prep_mod.clear_cache()
        with obs_mod.observe() as ob:
            family.compare_record(members, obs, engine="onehot")
            tot = ob.ledger.totals()
        return tot["upload_bytes"], prep_mod.cache_stats()["misses"]

    up1, _ = upload_bytes(one)
    up2, _ = upload_bytes(two)
    # The 2-member compare uploads the SAME stream bytes as the 1-member
    # set: one padded scoring buffer + one placed posterior span per
    # ORDER.  The only per-member uploads allowed are MODEL-sized (the
    # [K] island-mask vectors, 32 B each) — never stream-sized.
    assert up2 - up1 <= 64 * len(two), (up1, up2)
    assert up2 < up1 + obs.size  # no second copy of the stream went up


def test_dinuc_trains_reduced_stats_dense_twin_parity():
    """The K<=8 stats-envelope lift: the 32-state dinuc member's reduced
    (onehot) E-step agrees with the dense XLA twin — the same dense-twin
    parity pin the flagship's reduced stats carry."""
    from cpgisland_tpu.ops.forward_backward import batch_stats
    from cpgisland_tpu.train.backends import resolve_fb_engine

    from cpgisland_tpu.utils import codec

    params = presets.dinuc_cpg()
    assert resolve_fb_engine("onehot", params, "rescaled") == "onehot"
    rng = np.random.default_rng(29)
    # CHAIN-CONSISTENT pair records (a random pair stream is impossible
    # under the dinuc model's structural zeros and nan-collapses).
    rows = []
    for i in range(6):
        base = rng.integers(0, 4, size=513).astype(np.uint8)
        rows.append(codec.recode_pairs(base[1:], prev=int(base[0])))
    chunks = jnp.asarray(np.stack(rows))
    lengths = jnp.asarray(np.full(6, 512, np.int32))
    red = fb_pallas.batch_stats_pallas(params, chunks, lengths, onehot=True)
    dense = batch_stats(params, chunks, lengths, mode="rescaled")
    np.testing.assert_allclose(
        np.asarray(red.trans), np.asarray(dense.trans), rtol=2e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(red.emit), np.asarray(dense.emit), rtol=2e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        float(red.loglik), float(dense.loglik), rtol=1e-5
    )


# ---------------------------------------------------------------------------
# serve: stacked compare + mixed-model decode flushes


def _registry_with(names_and_members):
    from cpgisland_tpu.serve.session import ModelRegistry, Session

    sess = Session(presets.durbin_cpg8(), name="t", private_breaker=True)
    reg = ModelRegistry(sess)
    for m in names_and_members:
        reg.register(m, engine="onehot")
    return sess, reg


def _broker(reg, sess, **cfg):
    from cpgisland_tpu.serve.broker import BrokerConfig, RequestBroker

    defaults = dict(flush_symbols=1 << 15, flush_deadline_s=0.0)
    defaults.update(cfg)
    return RequestBroker(sess, BrokerConfig(**defaults), registry=reg)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_serve_compare_flush_stacked_parity():
    """A compare flush through the stacked dispatch returns the same
    loglik/odds/winner calls as the sequential arm (a stacked=False
    broker) AND as a direct compare_record — the serve-side bit-identity
    pin (runs under the graftsync LockTracker when CPGISLAND_TRACKSYNC=1)."""
    members = _member_objs(2)
    obs = np.random.default_rng(31).integers(0, 4, size=8000).astype(np.uint8)

    results = {}
    for stacked in (True, False):
        sess, reg = _registry_with(members)
        broker = _broker(reg, sess, stacked=stacked)
        broker.submit(
            request_id=1, tenant="t0", kind="compare", symbols=obs,
            name="r1", models=("durbin8", "rand1"),
        )
        (res,) = broker.drain()
        assert res.ok, res.error
        results[stacked] = res
    a, b = results[True], results[False]
    assert a.compare == b.compare
    np.testing.assert_array_equal(a.calls.beg, b.calls.beg)
    np.testing.assert_array_equal(a.calls.end, b.calls.end)
    direct = family.compare_record(
        members, obs, record="r1", engine="onehot", stacked=False
    )
    assert a.compare["models"]["durbin8"]["loglik"] == direct.member(
        "durbin8"
    ).loglik


def test_serve_mixed_model_decode_flush_stacked():
    """Mixed-model decode flush: batch-eligible decode requests of two
    onehot models coalesce into ONE stacked flat stream (route
    'flat-stacked'); island calls equal the sequential per-model flush on
    the same requests (tie-free seeds — the flat decoder's pinned
    rounding-tie contract, PARITY.md C10)."""
    members = _member_objs(2)
    rng = np.random.default_rng(37)
    recs = {
        "durbin8": [rng.integers(0, 4, size=n).astype(np.uint8)
                    for n in (900, 1500)],
        "rand1": [rng.integers(0, 4, size=n).astype(np.uint8)
                  for n in (1100, 700)],
    }

    def run(stacked):
        sess, reg = _registry_with(members)
        broker = _broker(reg, sess, stacked=stacked)
        rid = 0
        for model, rows in recs.items():
            for r in rows:
                rid += 1
                broker.submit(
                    request_id=rid, tenant="t0", kind="decode", symbols=r,
                    name=f"{model}:{rid}", model=model,
                )
        out = {r.id: r for r in broker.drain()}
        assert all(r.ok for r in out.values())
        return out

    st = run(True)
    sq = run(False)
    assert {r.route for r in st.values()} == {"flat-stacked"}
    assert "flat-stacked" not in {r.route for r in sq.values()}
    for rid in st:
        np.testing.assert_array_equal(st[rid].calls.beg, sq[rid].calls.beg)
        np.testing.assert_array_equal(st[rid].calls.end, sq[rid].calls.end)
        np.testing.assert_array_equal(
            st[rid].calls.gc_content, sq[rid].calls.gc_content
        )


def test_serve_stacked_decode_needs_two_models():
    """A flush where only ONE model contributes batch-eligible decode
    requests never stacks (nothing to share a launch with) — requests
    take the normal per-model routes.  (Cross-alphabet stacking is
    unreachable by construction: order-2 members are compare-only at
    admission, so decode flushes only ever see the 4-symbol base
    alphabet — the `_flush_decode_stacked` alphabet guard is defensive.)"""
    members = _member_objs(2)
    sess, reg = _registry_with(members)
    broker = _broker(reg, sess, stacked=True)
    rng = np.random.default_rng(41)
    for rid, n in ((1, 900), (2, 1300)):
        broker.submit(
            request_id=rid, tenant="t0", kind="decode",
            symbols=rng.integers(0, 4, size=n).astype(np.uint8),
            name=f"a{rid}", model="durbin8",
        )
    out = {r.id: r for r in broker.drain()}
    assert all(r.ok for r in out.values())
    assert "flat-stacked" not in {r.route for r in out.values()}


# ---------------------------------------------------------------------------
# graftcost: the de-stacking regression is a red build


def test_destacked_fixture_fails_pass_pin(tmp_path):
    """A planted DE-stacked multi-model posterior (per-member sequential
    scans instead of the one stacked scan) must fail the cost lockfile
    naming the regrown T-scaling passes — the r12 anti-regression, same
    shape as r9's cost_regrown_pass fixture."""
    from cpgisland_tpu.analysis import contracts, cost_contracts, costmodel

    members = _cast(3)
    mask = jnp.asarray((np.arange(8) < 4).astype(np.float32))
    masks = (mask,) * 3

    def make_stacked(scale: int = 1):
        import numpy as _np

        o = jnp.asarray(
            _np.random.default_rng(0).integers(
                0, 4, size=4096 * scale
            ).astype(_np.uint8)
        )
        fn = lambda o: fb_pallas._seq_posterior_core_stacked(
            members, o, o.shape[0], masks, 512, 256, axis=None
        )[0]
        return fn, (o,), None

    def make_destacked(scale: int = 1):
        import numpy as _np

        o = jnp.asarray(
            _np.random.default_rng(0).integers(
                0, 4, size=4096 * scale
            ).astype(_np.uint8)
        )

        def fn(o):
            outs = []
            for p in members:
                outs.append(
                    fb_pallas._seq_posterior_core(
                        p, o, o.shape[0], mask, 512, 256, axis=None,
                        onehot=True,
                    )[0]
                )
            return jnp.stack(outs)

        return fn, (o,), None

    # Scales must clear the 128-lane padding plateau (the registry's own
    # posterior scales) or no scan's cost grows between geometries.
    stacked_entry = costmodel.trace_entry(
        contracts.Contract(
            name="fixture.stacked", make=make_stacked, base_symbols=4096,
            cost_scales=(16, 32),
        )
    )
    destacked_entry = costmodel.trace_entry(
        contracts.Contract(
            name="fixture.stacked", make=make_destacked, base_symbols=4096,
            cost_scales=(16, 32),
        )
    )
    # The structural quantity EXPECTED_PASSES pins: stacking keeps the
    # T-scaling pass count CONSTANT in N (2: products + fused fwd/bwd);
    # de-stacking regrows one pass set per member.
    assert stacked_entry.passes() == 2
    assert destacked_entry.passes() == 3 * 2
    fp = {"fixture.stacked": cost_contracts.fingerprint(stacked_entry)}
    lock_path = str(tmp_path / "COSTS.json")
    cost_contracts.write_lockfile(fp, lock_path, platform="cpu")
    live = {"fixture.stacked": cost_contracts.fingerprint(destacked_entry)}
    diff = cost_contracts.diff_costs(
        live, cost_contracts.load_lockfile(lock_path), "cpu"
    )
    assert not diff.ok
    assert any(
        "pass count 2 -> 6" in v and "drifting prims" in v
        for v in diff.violations
    ), diff.violations
