"""ISSUE 17 true one-pass reduced FB: the products pass folded in.

The matrix-carried kernel (fb_onehot._oh_fwdbwd_mat_kernel / its one-scan
XLA twin) runs the reduced forward AND backward chains in [2,2]
transfer-matrix form — 4 carry rows per direction — and emits per-lane
transfer totals itself, so the standalone products/boundary pass
disappears: posterior and exact-seq EM drop 2 -> 1 T-scaling passes.  The
true entry directions are applied per-position in scale-free elementwise
epilogues (contract_mat_streams) and the r7 reduced [NL,2,2] boundary
combine runs as an O(NL) epilogue over the kernel's own totals.

Pinned here: parity of the one-pass arm against the r9 fused arm, the r4
split arm, and the dense engine (conf, MPM paths, znorm stats, fused-EM
trajectories); span/continuation threading; ragged lane geometries; the
order-2 dinucleotide member (K=32 one-hot over the 16-symbol pair
alphabet); prepared-vs-inline bit-identity; zero fresh compiles at steady
state; the graftune consultation sites with bit-for-bit stale/absent
fallback; and the memmodel verdict that keeps the STACKED decoder on the
2-pass arm (the matrix kernel is M=3-infeasible at the 256-lane tile).

Off-TPU these run the arithmetic-identical XLA twins; the TPU suite run
(CPGISLAND_TEST_PLATFORM=axon) exercises the Pallas kernels against the
same assertions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu import tune
from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import sample_sequence
from cpgisland_tpu.ops import fb_pallas, prepared
from cpgisland_tpu.parallel.posterior import posterior_sharded
from cpgisland_tpu.tune import table as tune_table
from cpgisland_tpu.utils import codec

MASK8 = jnp.asarray(np.r_[np.ones(4), np.zeros(4)].astype(np.float32))


def _obs(rng, n):
    params = presets.durbin_cpg8()
    _, obs = sample_sequence(
        params, jax.random.PRNGKey(int(rng.integers(1 << 30))), n
    )
    return params, obs


def _pair_record(n, seed=0):
    rng = np.random.default_rng(seed)
    base = rng.integers(0, 4, size=n + 1).astype(np.uint8)
    return codec.recode_pairs(base[1:], prev=int(base[0]))


def _assert_stats_close(a, b, rtol=5e-5, atol=1e-3):
    np.testing.assert_allclose(np.asarray(a.init), np.asarray(b.init), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(a.trans), np.asarray(b.trans), rtol=rtol, atol=atol
    )
    np.testing.assert_allclose(
        np.asarray(a.emit), np.asarray(b.emit), rtol=rtol, atol=atol
    )
    assert float(a.loglik) == pytest.approx(float(b.loglik), rel=1e-5)
    assert int(a.n_seqs) == int(b.n_seqs)


# --- posterior: one-pass vs fused vs split vs dense --------------------------


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_posterior_conf_one_pass_parity(rng):
    params, obs = _obs(rng, 12001)  # ragged vs the lane geometry
    kw = dict(lane_T=2048, t_tile=512, onehot=True)
    c_split, _ = fb_pallas.seq_posterior_pallas(
        params, obs, obs.shape[0], MASK8, fused=False, **kw
    )
    c_fused, _ = fb_pallas.seq_posterior_pallas(
        params, obs, obs.shape[0], MASK8, fused=True, **kw
    )
    c_one, _ = fb_pallas.seq_posterior_pallas(
        params, obs, obs.shape[0], MASK8, one_pass=True, **kw
    )
    c_dense, _ = fb_pallas.seq_posterior_pallas(
        params, obs, obs.shape[0], MASK8, lane_T=2048, t_tile=512
    )
    np.testing.assert_allclose(np.asarray(c_one), np.asarray(c_split), atol=2e-5)
    np.testing.assert_allclose(np.asarray(c_one), np.asarray(c_fused), atol=2e-5)
    np.testing.assert_allclose(np.asarray(c_one), np.asarray(c_dense), atol=2e-5)


def test_posterior_one_pass_want_path(rng):
    """The MPM argmax is scale-free in the matrix-carried directions: paths
    must match the split arm exactly (same argmax inputs modulo per-position
    positive scales)."""
    params, obs = _obs(rng, 10000)
    kw = dict(lane_T=2048, t_tile=512, onehot=True, want_path=True)
    c_s, p_s = fb_pallas.seq_posterior_pallas(
        params, obs, obs.shape[0], MASK8, fused=False, **kw
    )
    c_o, p_o = fb_pallas.seq_posterior_pallas(
        params, obs, obs.shape[0], MASK8, one_pass=True, **kw
    )
    np.testing.assert_allclose(np.asarray(c_o), np.asarray(c_s), atol=2e-5)
    assert np.array_equal(np.asarray(p_o), np.asarray(p_s))


def test_posterior_one_pass_span_continuation(rng):
    """Span-threaded continuation (enter/exit dirs + prev_sym) through the
    one-pass arm matches the split arm — the entry direction enters only
    through the elementwise contraction epilogue, never the kernel."""
    params, obs = _obs(rng, 12000)
    span = 6000
    piece = obs[span:]
    enter = np.abs(np.random.default_rng(1).normal(size=8)).astype(np.float32)
    enter /= enter.sum()
    kw = dict(
        enter_dir=jnp.asarray(enter), exit_dir=None, first=False,
        lane_T=2048, t_tile=512, onehot=True,
        prev_sym=jnp.int32(int(obs[span - 1])),
    )
    c_s, _ = fb_pallas.seq_posterior_pallas(
        params, piece, piece.shape[0], MASK8, fused=False, **kw
    )
    c_o, _ = fb_pallas.seq_posterior_pallas(
        params, piece, piece.shape[0], MASK8, one_pass=True, **kw
    )
    np.testing.assert_allclose(np.asarray(c_o), np.asarray(c_s), atol=2e-5)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_posterior_sharded_one_pass_parity(rng):
    """The driver entry over the full device mesh: one_pass=True vs False,
    plus the dense-engine cross-check."""
    params, obs = _obs(rng, 8 * 1024 + 77)
    isl = (0, 1, 2, 3)
    c_f, _ = posterior_sharded(
        params, np.asarray(obs), isl, engine="onehot", one_pass=False
    )
    c_o, _ = posterior_sharded(
        params, np.asarray(obs), isl, engine="onehot", one_pass=True
    )
    c_x, _ = posterior_sharded(
        params, np.asarray(obs), isl, engine="xla", block_size=256
    )
    np.testing.assert_allclose(np.asarray(c_o), np.asarray(c_f), atol=2e-5)
    np.testing.assert_allclose(np.asarray(c_o), np.asarray(c_x), atol=2e-5)


# --- EM: one-pass znorm stats vs the 2-pass arms -----------------------------


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_seq_stats_one_pass_parity(rng):
    params, obs = _obs(rng, 12001)
    kw = dict(lane_T=2048, onehot=True)
    s_split = fb_pallas.seq_stats_pallas(
        params, obs, obs.shape[0], fused=False, **kw
    )
    s_fused = fb_pallas.seq_stats_pallas(
        params, obs, obs.shape[0], fused=True, **kw
    )
    s_one = fb_pallas.seq_stats_pallas(
        params, obs, obs.shape[0], one_pass=True, **kw
    )
    s_dense = fb_pallas.seq_stats_pallas(params, obs, obs.shape[0], lane_T=2048)
    _assert_stats_close(s_one, s_split)
    _assert_stats_close(s_one, s_fused)
    _assert_stats_close(s_one, s_dense)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_seq_stats_one_pass_dinuc32(rng):
    """The order-2 family member: K=32 one-hot over the 16-symbol pair
    alphabet rides the same matrix-carried kernel (pow2-S reduced stats)."""
    params = presets.dinuc_cpg()
    obs = jnp.asarray(_pair_record(8000, seed=11).astype(np.int32))
    kw = dict(lane_T=1024, onehot=True)
    s_split = fb_pallas.seq_stats_pallas(
        params, obs, obs.shape[0], fused=False, **kw
    )
    s_one = fb_pallas.seq_stats_pallas(
        params, obs, obs.shape[0], one_pass=True, **kw
    )
    _assert_stats_close(s_one, s_split)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_seq_backend_one_pass_fit_trajectory(rng):
    """End-to-end: a Baum-Welch fit through SeqBackend(one_pass=True)
    reproduces the 2-pass trajectory (the training-path acceptance for the
    products fold)."""
    from cpgisland_tpu.train import baum_welch
    from cpgisland_tpu.train.backends import SeqBackend
    from cpgisland_tpu.utils import chunking

    params, obs = _obs(rng, 8 * 1024)
    chunked = chunking.Chunked(
        chunks=np.asarray(obs)[None, :],
        lengths=np.asarray([obs.shape[0]], np.int32),
        total=obs.shape[0],
    )
    res = {}
    for one_pass in (False, True):
        backend = SeqBackend(
            engine="onehot", lane_T=512, t_tile=256, one_pass=one_pass
        )
        res[one_pass] = baum_welch.fit(
            params, chunked, num_iters=2, convergence=0.0, backend=backend
        )
    np.testing.assert_allclose(
        np.asarray(res[True].logliks), np.asarray(res[False].logliks),
        rtol=1e-5,
    )


@pytest.mark.slow
def test_seq2d_backend_one_pass_parity(rng):
    """The 2-D (records x time) whole-sequence layout threads one_pass
    through sharded_stats2d_fn — ragged two-record group."""
    from cpgisland_tpu.train import backends
    from cpgisland_tpu.utils import chunking

    params = presets.durbin_cpg8()
    r = np.random.default_rng(5)
    obs2 = r.integers(0, 4, size=(2, 1 << 12), dtype=np.uint8)
    lens2 = np.asarray([1 << 12, (1 << 12) - 77], np.int32)
    stats = {}
    for one_pass in (False, True):
        be = backends.Seq2DBackend(engine="onehot", one_pass=one_pass)
        ch = be.prepare(chunking.Chunked(
            chunks=obs2, lengths=lens2, total=int(lens2.sum())
        ))
        o, l = be.place(ch.chunks, ch.lengths)
        stats[one_pass] = be(params, o, l)
    _assert_stats_close(stats[True], stats[False])


# --- prepared streams + dispatch surface -------------------------------------


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_one_pass_prepared_vs_inline_bit_identical(rng):
    """The matrix kernel consumes the SAME pair2/pairn2 prepared fields as
    the 2-pass arm — no new prepared stream, so prepared-vs-inline stays
    bit-identical on the one-pass arm too."""
    params, obs = _obs(rng, 6000)
    kw = dict(lane_T=512, t_tile=256, onehot=True)
    prep = prepared.for_seq(4, obs, 6000, **kw)
    s_inline = fb_pallas.seq_stats_pallas(
        params, obs, 6000, one_pass=True, **kw
    )
    s_prep = fb_pallas.seq_stats_pallas(
        params, obs, 6000, one_pass=True, prepared=prep, **kw
    )
    for f in ("init", "trans", "emit"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s_inline, f)), np.asarray(getattr(s_prep, f))
        )
    assert float(s_inline.loglik) == float(s_prep.loglik)

    c_inline, _ = fb_pallas.seq_posterior_pallas(
        params, obs, 6000, MASK8, one_pass=True, **kw
    )
    c_prep, _ = fb_pallas.seq_posterior_pallas(
        params, obs, 6000, MASK8, one_pass=True, prepared=prep, **kw
    )
    np.testing.assert_array_equal(np.asarray(c_inline), np.asarray(c_prep))


def test_one_pass_zero_fresh_compiles_steady_state(rng):
    """Steady state on the one-pass arm: new params (an M-step away), same
    shapes and prep — zero fresh compiles on both entries."""
    import dataclasses

    from cpgisland_tpu import obs as obs_mod

    params, obs = _obs(rng, 6000)
    kw = dict(lane_T=512, t_tile=256, onehot=True)
    prep = prepared.for_seq(4, obs, 6000, **kw)
    jax.block_until_ready(fb_pallas.seq_stats_pallas(
        params, obs, 6000, one_pass=True, prepared=prep, **kw
    ).trans)
    jax.block_until_ready(fb_pallas.seq_posterior_pallas(
        params, obs, 6000, MASK8, one_pass=True, prepared=prep, **kw
    )[0])
    params2 = dataclasses.replace(params, log_pi=params.log_pi - 1e-6)
    with obs_mod.no_new_compiles("one-pass-steady-state"):
        jax.block_until_ready(fb_pallas.seq_stats_pallas(
            params2, obs, 6000, one_pass=True, prepared=prep, **kw
        ).trans)
        jax.block_until_ready(fb_pallas.seq_posterior_pallas(
            params2, obs, 6000, MASK8, one_pass=True, prepared=prep, **kw
        )[0])


# --- graftune consultation + bit-for-bit fallback ----------------------------


@pytest.fixture
def tmp_table(tmp_path):
    path = str(tmp_path / "TUNING.json")
    tune.set_table_path(path)
    try:
        yield path
    finally:
        tune.set_table_path(None)
        tune.generation()


def _plant(task, value, *, costs_entries, fingerprint=None):
    key = tune_table.entry_key(task, None, None, 1)
    entry = tune_table.make_entry(
        task, value, legacy=None, costs_entries=costs_entries,
        applied=True, projection=True,
    )
    if fingerprint is not None:
        entry["costs_fingerprint"] = fingerprint
    tune_table.write_entries({key: entry}, platform="cpu")
    return key


def test_one_pass_default_consultation(tmp_table):
    from cpgisland_tpu.train.backends import Seq2DBackend, SeqBackend

    # Shipped legacy: the 2-pass fused arm (the one-pass trade is only
    # decidable on silicon).
    assert tune.default_one_pass("posterior") is False
    assert tune.default_one_pass("em_seq") is False
    assert SeqBackend().one_pass is False
    assert Seq2DBackend().one_pass is False
    _plant("one_pass.em_seq", True, costs_entries=["em.seq.onehot.onepass"])
    assert tune.default_one_pass("em_seq") is True
    assert SeqBackend().one_pass is True
    assert Seq2DBackend().one_pass is True
    # Explicit always wins.
    assert SeqBackend(one_pass=False).one_pass is False


def test_one_pass_stale_fingerprint_falls_back_bitwise(tmp_table, rng):
    """A fingerprint-drifted one_pass winner must NOT route: the default
    arm stays bit-for-bit the legacy 2-pass fused arm."""
    params, obs = _obs(rng, 8 * 1024)
    isl = (0, 1, 2, 3)
    kw = dict(engine="onehot", lane_T=512, t_tile=256)
    c_false, _ = posterior_sharded(
        params, np.asarray(obs), isl, one_pass=False, **kw
    )
    _plant(
        "one_pass.posterior", True,
        costs_entries=["posterior.onehot.onepass"],
        fingerprint="sha256:deadbeefdeadbeef",
    )
    assert tune.default_one_pass("posterior") is False
    c_default, _ = posterior_sharded(params, np.asarray(obs), isl, **kw)
    np.testing.assert_array_equal(np.asarray(c_default), np.asarray(c_false))
    rep = tune_table.table_report(platform="cpu")
    assert rep["stale"] == 1
    assert "fingerprint drifted" in rep["stale_entries"][0]["reason"]


def test_one_pass_fresh_winner_routes(tmp_table, rng):
    """A FRESH applied winner flips the default arm to the one-pass kernel:
    the default output becomes bit-identical to explicit one_pass=True."""
    params, obs = _obs(rng, 8 * 1024)
    isl = (0, 1, 2, 3)
    kw = dict(engine="onehot", lane_T=512, t_tile=256)
    c_true, _ = posterior_sharded(
        params, np.asarray(obs), isl, one_pass=True, **kw
    )
    _plant(
        "one_pass.posterior", True,
        costs_entries=["posterior.onehot.onepass"],
    )
    assert tune.default_one_pass("posterior") is True
    c_default, _ = posterior_sharded(params, np.asarray(obs), isl, **kw)
    np.testing.assert_array_equal(np.asarray(c_default), np.asarray(c_true))


# --- memmodel: the stacked verdict -------------------------------------------


def test_matrix_kernel_memmodel_verdicts():
    """The matrix kernel's VMEM row: feasible at M=1/256-lane tiles,
    INFEASIBLE at the stacked M=3 — the reason posterior_sharded_stacked
    and the stacked decoder stay on the 2-pass arm."""
    from cpgisland_tpu.analysis import memmodel

    k1 = memmodel.Knobs(lane_tile=256)
    v1 = memmodel.feasible("fb.fwdbwdmat.onehot", k1)
    assert v1.ok, (v1.total, v1.limit)
    k3 = memmodel.Knobs(lane_tile=256, stacked_m=3)
    v3 = memmodel.feasible("fb.fwdbwdmat.onehot", k3)
    assert not v3.ok
    assert v3.total > v1.total
    assert memmodel.max_stacked_m("fb.fwdbwdmat.onehot", k1) == 1
    # Not a stacked-routing kernel: the stacked drivers never consult it.
    assert "fb.fwdbwdmat.onehot" not in memmodel.STACKED_KERNELS
