"""Sequence-parallel sharded Viterbi vs single-device decoders (8-dev CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops import viterbi as V
from cpgisland_tpu.ops import viterbi_parallel as VP
from cpgisland_tpu.parallel import decode as PD
from cpgisland_tpu.parallel.mesh import make_mesh


def _path_score(params, obs, path):
    lp, lA, lB = (np.asarray(x, np.float64) for x in (params.log_pi, params.log_A, params.log_B))
    s = lp[path[0]] + lB[path[0], obs[0]]
    for t in range(1, len(obs)):
        s += lA[path[t - 1], path[t]] + lB[path[t], obs[t]]
    return s


def test_eight_devices_present():
    """The CI environment contract (virtual CPU mesh); real single-chip
    hardware runs are exempt."""
    import os

    if os.environ.get("CPGISLAND_TEST_PLATFORM", "cpu") != "cpu":
        pytest.skip("device-count contract applies to the virtual CPU mesh")
    assert len(jax.devices()) == 8


def test_sharded_matches_single_device_durbin(rng):
    params = presets.durbin_cpg8()
    bg = rng.choice([0, 3], size=3000)
    island = np.tile([1, 2], 400)
    obs = np.concatenate([bg, island, bg]).astype(np.int32)
    single = np.asarray(VP.viterbi_parallel(params, jnp.asarray(obs), return_score=False))
    sharded = PD.viterbi_sharded(params, obs, block_size=64)
    np.testing.assert_array_equal(single, sharded)


def test_sharded_achieves_optimal_score_random_model(rng):
    pi = rng.dirichlet(np.ones(4))
    A = rng.dirichlet(np.ones(4), size=4)
    B = rng.dirichlet(np.ones(4), size=4)
    params = HmmParams.from_probs(pi, A, B)
    obs = rng.integers(0, 4, size=2048).astype(np.int32)
    _, s_opt = V.viterbi(params, jnp.asarray(obs))
    path = PD.viterbi_sharded(params, obs, block_size=32)
    assert _path_score(params, obs, path) == pytest.approx(float(s_opt), abs=2e-2, rel=1e-5)


def test_sharded_pads_uneven_lengths(rng):
    params = presets.durbin_cpg8()
    obs = rng.integers(0, 4, size=1234).astype(np.int32)  # not divisible by 8*64
    path = PD.viterbi_sharded(params, obs, block_size=64)
    assert path.shape == (1234,)
    single = np.asarray(VP.viterbi_parallel(params, jnp.asarray(obs), return_score=False))
    # Same achieved score (ties may reorder path choices).
    assert _path_score(params, obs, path) == pytest.approx(
        _path_score(params, obs, single), abs=2e-2
    )


def test_island_not_clipped_across_shard_boundary(rng):
    """An island spanning a shard boundary must come out contiguous —
    the artifact the reference exhibits at 1 MiB chunk boundaries."""
    from cpgisland_tpu.ops import islands as I

    params = presets.durbin_cpg8()
    n_dev = 8
    block = 32
    # Total 8 shards of 512: put one island exactly straddling shards 3|4.
    L = 512
    T = n_dev * L
    obs = np.asarray(rng.choice([0, 3], size=T), dtype=np.int32)
    mid = 4 * L
    island = np.tile([1, 2], 300)
    obs[mid - 300 : mid + 300] = island
    path = PD.viterbi_sharded(params, obs, block_size=block)
    calls = I.call_islands(path, compat=False)
    assert len(calls) == 1
    assert calls.beg[0] <= mid - 250 and calls.end[0] >= mid + 250


def test_explicit_small_mesh(rng):
    from conftest import require_devices

    require_devices(4)
    params = presets.durbin_cpg8()
    mesh = make_mesh(4, axis="seq")
    obs = rng.integers(0, 4, size=1024).astype(np.int32)
    path = PD.viterbi_sharded(params, obs, mesh=mesh, block_size=32)
    single = np.asarray(VP.viterbi_parallel(params, jnp.asarray(obs), return_score=False))
    assert _path_score(params, obs, path) == pytest.approx(
        _path_score(params, obs, single), abs=1e-2
    )


def test_spanwise_decode_bit_identical_to_oneshot(rng):
    """viterbi_sharded_spans threads boundary messages across spans, so a
    record decoded in 5 spans must equal the one-shot sharded decode exactly
    (VERDICT r2 item 3: CLEAN_DECODE_SPAN stops being an exactness boundary)."""
    params = presets.durbin_cpg8()
    T = 5 * 4096 + 777  # 6 spans incl. a ragged tail
    bg = rng.choice([0, 3], size=T).astype(np.int32)
    obs = bg.copy()
    # Plant islands straddling two span boundaries (4096, 8192) so the old
    # restart artifact would have flipped positions there.
    for mid in (4096, 8192, 3 * 4096 + 100):
        obs[mid - 200 : mid + 200] = np.tile([1, 2], 200)
    oneshot = PD.viterbi_sharded(params, obs, block_size=64)
    spans = PD.viterbi_sharded_spans(params, obs, span=4096, block_size=64)
    assert [p.shape[0] for p in spans] == [4096] * 5 + [777]
    np.testing.assert_array_equal(np.concatenate(spans), oneshot)


def test_spanwise_decode_short_input_delegates(rng):
    params = presets.durbin_cpg8()
    obs = rng.integers(0, 4, size=1000).astype(np.int32)
    spans = PD.viterbi_sharded_spans(params, obs, span=4096, block_size=32)
    assert len(spans) == 1
    np.testing.assert_array_equal(
        spans[0], PD.viterbi_sharded(params, obs, block_size=32)
    )


def test_spanwise_decode_random_model_matches_f64_dp(rng):
    """Span stitching on a tie-prone random model still achieves the f64-DP
    optimal score."""
    pi = rng.dirichlet(np.ones(4))
    A = rng.dirichlet(np.ones(4), size=4)
    B = rng.dirichlet(np.ones(4), size=4)
    params = HmmParams.from_probs(pi, A, B)
    obs = rng.integers(0, 4, size=3000).astype(np.int32)
    _, s_opt = V.viterbi(params, jnp.asarray(obs))
    spans = PD.viterbi_sharded_spans(params, obs, span=1024, block_size=32)
    assert _path_score(params, obs, np.concatenate(spans)) == pytest.approx(
        float(s_opt), abs=2e-2, rel=1e-5
    )


def test_initialize_multihost_single_process_noop():
    """Without a cluster environment (and no explicit args) this is a no-op
    that reports the device count; explicit-but-broken args still raise."""
    from cpgisland_tpu.parallel.mesh import initialize_multihost

    assert initialize_multihost() == len(jax.devices())
    with pytest.raises(Exception):
        initialize_multihost(num_processes=2, process_id=0)  # no coordinator
