"""Published figures cannot drift from the captured bench artifact.

VERDICT r2 #6 + r3 #8: README/BASELINE headline figures must derive from a
captured machine-readable artifact, not hand-copying.  tools/pubnum.py owns
the parse + marker check; this test runs it, and additionally:

- cross-checks EVERY per-kernel figure the latest driver BENCH_r*.json tail
  carries against the captured artifact within 20% (run-to-run TPU noise is
  real — CLAUDE.md notes transient slowdowns — but a figure off by >20%
  means the docs describe a different build);
- fails when the captured artifact's round suffix LAGS the newest driver
  BENCH_r*.json — a stale capture can't keep certifying newer code.
"""

import glob
import json
import os
import re
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def _latest_driver():
    bench_files = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    if not bench_files:
        return None, None
    with open(bench_files[-1]) as f:
        return bench_files[-1], json.load(f)


def test_docs_match_captured_artifact():
    import pubnum

    vals = pubnum.parse_captured(REPO)
    problems = pubnum.check_docs(vals, REPO)
    assert not problems, "\n".join(problems)


def test_captured_artifact_not_stale():
    """The capture's round suffix must not lag the newest driver record:
    bench_captured_r{N} with BENCH_r{M}.json present and N < M means the
    published figures certify a build at least one round old."""
    import pubnum

    _, _, cap_round = pubnum.capture_paths(REPO)
    path, _ = _latest_driver()
    if path is None:
        pytest.skip("no driver BENCH_r*.json present")
    driver_round = int(re.search(r"BENCH_r(\d+)\.json$", path).group(1))
    assert cap_round >= driver_round, (
        f"captured artifact is r{cap_round:02d} but the newest driver record "
        f"is r{driver_round:02d} — re-run `python bench.py --extended` with "
        f"captures to bench_captured_r{driver_round:02d}.* and then "
        "`python tools/pubnum.py --write`"
    )


def test_driver_tail_figures_agree_with_capture():
    """EVERY figure the latest driver tail carries (decode/em Msym/s, the
    north-star split) must agree with the captured artifact within 20% —
    not just the headline seconds (VERDICT r3 #8).

    Enforced only when the capture and the newest driver record are the SAME
    round: that is the same-build drift this check exists to catch.  A
    capture one round NEWER than the driver record is the normal mid-round
    state after performance work (e.g. the r4 one-hot kernels moved decode
    +84% over the r3 driver tail — a real improvement, not drift); the
    staleness test above still forbids the opposite direction, and the next
    driver record re-arms this check against the same build."""
    import pubnum

    vals = pubnum.parse_captured(REPO)
    _, _, cap_round = pubnum.capture_paths(REPO)
    path, driver = _latest_driver()
    if path is None:
        pytest.skip("no driver BENCH_r*.json present")
    driver_round = int(re.search(r"BENCH_r(\d+)\.json$", path).group(1))
    if cap_round > driver_round:
        pytest.skip(
            f"capture r{cap_round:02d} is newer than the driver record "
            f"r{driver_round:02d} (mid-round performance work) — the check "
            "re-arms when the driver's own record for this round lands"
        )
    tail_vals = pubnum.parse_lines(driver["tail"].splitlines())
    tail_vals["northstar_value"] = driver["parsed"]["value"]
    checked = 0
    problems = []
    for key, dv in tail_vals.items():
        if key not in vals or not isinstance(dv, (int, float)) or dv == 0:
            continue
        cv = vals[key]
        checked += 1
        if abs(dv - cv) / abs(dv) >= 0.20:
            problems.append(
                f"{key}: driver {path} says {dv}, captured artifact says "
                f"{cv} (>20% apart) — re-capture (tools/pubnum.py --write)"
            )
    assert checked >= 3, f"driver tail carried too few figures ({checked})"
    assert not problems, "\n".join(problems)
