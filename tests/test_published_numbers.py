"""Published figures cannot drift from the captured bench artifact.

VERDICT r2 #6: README/BASELINE headline figures must derive from a captured
machine-readable artifact, not hand-copying.  tools/pubnum.py owns the
parse + marker check; this test runs it, and additionally cross-checks the
north-star seconds against the LATEST driver BENCH_r*.json within a variance
band (run-to-run TPU noise is real — CLAUDE.md notes transient slowdowns —
but a figure drifting by >35% means the docs describe a different build).
"""

import glob
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_docs_match_captured_artifact():
    import pubnum

    vals = pubnum.parse_captured(REPO)
    problems = pubnum.check_docs(vals, REPO)
    assert not problems, "\n".join(problems)


def test_northstar_agrees_with_latest_driver_record():
    import pubnum

    vals = pubnum.parse_captured(REPO)
    bench_files = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    if not bench_files:
        pytest.skip("no driver BENCH_r*.json present")
    with open(bench_files[-1]) as f:
        driver = json.load(f)
    driver_val = driver["parsed"]["value"]
    doc_val = vals["northstar_value"]
    assert abs(driver_val - doc_val) / driver_val < 0.35, (
        f"doc north star {doc_val}s vs driver {bench_files[-1]} "
        f"{driver_val}s — re-capture the artifact (tools/pubnum.py --write)"
    )
