"""The Layer-4 self-scan gate, pinned the way ``test_graftcheck_self.py``
pins Layers 1-3: the repo is clean under its own concurrency rules, the
cross-module lock-order graph is acyclic, every known lock is actually
discovered (the scan cannot silently go blind), zero stale sync waivers,
and the real pre-existing findings this layer fixed in-code (the
thread-unsafe obs ledger counters, the unlocked Observer event state, the
unlocked prepared cache) STAY fixed — their locks must keep appearing in
the model.
"""

import os

import pytest

from cpgisland_tpu.analysis import run_lint, synccheck
from cpgisland_tpu.analysis.config import SYNC_BLOCKING_OK, SYNC_UNGUARDED

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "cpgisland_tpu")

SYNC_RULES = (
    "sync-guarded-by",
    "sync-lock-order",
    "sync-blocking-under-lock",
    "sync-thread-lifecycle",
)


def test_sync_self_scan_clean():
    result = run_lint([PKG], base=REPO, rule_names=list(SYNC_RULES))
    assert result.files_checked > 40
    bad = [f.format() for f in result.unwaived]
    assert bad == [], "\n".join(bad)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_sync_waivers_none_stale_and_all_justified():
    result = run_lint([PKG], base=REPO)
    stale_sync = [
        (rel, w) for rel, w in result.unused_waivers
        if any(r.startswith("sync-") for r in w.rules)
    ]
    assert stale_sync == [], stale_sync
    for f in result.waived:
        if f.rule in SYNC_RULES:
            assert f.waiver_reason, f.format()


def test_registered_exemptions_all_carry_reasons():
    for registry in (SYNC_UNGUARDED, SYNC_BLOCKING_OK):
        for suffix, entries in registry.items():
            assert entries, f"empty registry section {suffix}"
            for key, reason in entries.items():
                assert reason and len(reason) > 20, (suffix, key)


def test_lock_order_graph_acyclic_on_tree():
    rep = synccheck.run_sync()
    assert rep.ok, [f.format() for f in rep.findings]
    assert rep.files_checked > 40


def test_known_locks_all_discovered():
    """The serve subsystem's locks must all be in the model — a refactor
    that renames one out of discovery would silently shrink the checked
    surface (same defense as the hot-path registry layout test)."""
    rep = synccheck.run_sync()
    labels = {lk.label for lk in rep.locks}
    for expected in (
        "cpgisland_tpu/serve/broker.py::RequestBroker._lock",
        "cpgisland_tpu/serve/session.py::Session._lock",
        "cpgisland_tpu/serve/transport.py::ResponseRouter._lock",
        "cpgisland_tpu/serve/transport.py::_MuxClient._lock",
        "cpgisland_tpu/resilience/breaker.py::EngineBreaker._lock",
        # The PR 15 fleet fault-domain locks: the pool's failover queue,
        # the per-device health machines, the two-phase journal, and the
        # graftfault plan state — all must stay inside the model.
        "cpgisland_tpu/serve/fleet.py::DevicePool._lock",
        "cpgisland_tpu/serve/fleet.py::DeviceHealth._lock",
        # The PR 20 routing-tier locks: the router's owner/adopted maps
        # and the per-host health machines (DeviceHealth one fault-domain
        # level up) — both documented leaves.
        "cpgisland_tpu/serve/router.py::RequestRouter._lock",
        "cpgisland_tpu/serve/router.py::HostHealth._lock",
        "cpgisland_tpu/resilience/manifest.py::RunManifest._lock",
        "cpgisland_tpu/resilience/faultplan.py::_LOCK",
        "cpgisland_tpu/resilience/faultplan.py::FaultPlan._lock",
        # The pre-existing findings fixed in-code by this layer:
        "cpgisland_tpu/obs/ledger.py::Ledger._lock",
        "cpgisland_tpu/obs/__init__.py::Observer._events_lock",
        "cpgisland_tpu/ops/prepared.py::_CACHE_LOCK",
        "cpgisland_tpu/utils/native.py::_lock",
    ):
        assert expected in labels, (expected, sorted(labels))


def test_documented_lock_order_edges_observed():
    """The serve package docstring's global order (session -> breaker) is
    what the static graph actually sees — the documentation and the model
    cannot drift apart silently."""
    rep = synccheck.run_sync()
    edges = {(e.src.label, e.dst.label) for e in rep.edges}
    assert (
        "cpgisland_tpu/serve/session.py::Session._lock",
        "cpgisland_tpu/resilience/breaker.py::EngineBreaker._lock",
    ) in edges, sorted(edges)
    # PR 15: the write-ahead journal order (broker admission holds the cv
    # while the admit line lands) — broker -> journal, never the reverse.
    assert (
        "cpgisland_tpu/serve/broker.py::RequestBroker._lock",
        "cpgisland_tpu/resilience/manifest.py::RunManifest._lock",
    ) in edges, sorted(edges)
    for src, _dst in edges:
        assert "RunManifest" not in src, "the journal lock must stay a leaf"
    # And no edge ever leaves a _MuxClient write lock (documented leaf).
    for src, dst in edges:
        assert "_MuxClient" not in src, (src, dst)


def test_broker_cv_aliases_to_broker_lock():
    """``RequestBroker._cv`` is ``Condition(self._lock)`` — one mutex.  The
    model must alias them into ONE lock group (two identities would let an
    inverted cv-vs-lock nesting hide from the cycle check)."""
    models = synccheck.build_models(
        [os.path.join(PKG, "serve", "broker.py")], base=REPO
    )
    locks = models[0].class_locks["RequestBroker"]
    # Frozen-dataclass equality IS group identity for held-set membership.
    assert locks["_cv"] == locks["_lock"]
    assert locks["_cv"].name == "_lock"
