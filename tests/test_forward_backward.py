"""E-step statistics vs the NumPy oracle, both numerics modes, pad handling."""

import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops import forward_backward as FB
from tests import oracle


def _random_model(rng, k=3, m=4):
    pi = rng.dirichlet(np.ones(k))
    A = rng.dirichlet(np.ones(k), size=k)
    B = rng.dirichlet(np.ones(m), size=k)
    return pi, A, B


def _oracle_stats(pi, A, B, obs):
    gamma, xi_sum, ll = oracle.forward_backward_oracle(pi, A, B, obs)
    emit = np.zeros_like(B)
    for s in range(B.shape[1]):
        emit[:, s] = gamma[np.asarray(obs) == s].sum(axis=0)
    return gamma[0], xi_sum, emit, ll


@pytest.mark.parametrize("mode", ["log", "rescaled"])
@pytest.mark.parametrize("T", [1, 2, 5, 64])
def test_chunk_stats_matches_oracle(rng, mode, T):
    for _ in range(4):
        pi, A, B = _random_model(rng)
        obs = rng.integers(0, 4, size=T)
        params = HmmParams.from_probs(pi, A, B)
        st = FB.chunk_stats(params, jnp.asarray(obs), jnp.int32(T), mode=mode)
        g0, xi, emit, ll = _oracle_stats(pi, A, B, obs)
        np.testing.assert_allclose(np.asarray(st.init), g0, atol=2e-4)
        # 5e-3: TPU transcendentals (exp/log in the log-semiring path) are
        # ~2e-5 relative; counts of magnitude ~10 land near 4e-3 absolute.
        np.testing.assert_allclose(np.asarray(st.trans), xi, atol=5e-3)
        np.testing.assert_allclose(np.asarray(st.emit), emit, atol=5e-3)
        assert float(st.loglik) == pytest.approx(ll, abs=2e-2, rel=1e-4)
        assert int(st.n_seqs) == 1


@pytest.mark.parametrize("mode", ["log", "rescaled"])
def test_padded_equals_truncated(rng, mode):
    pi, A, B = _random_model(rng)
    params = HmmParams.from_probs(pi, A, B)
    obs = rng.integers(0, 4, size=40)
    full = FB.chunk_stats(params, jnp.asarray(obs), jnp.int32(40), mode=mode)
    padded = np.concatenate([obs, np.full(24, 4)]).astype(np.int32)
    part = FB.chunk_stats(params, jnp.asarray(padded), jnp.int32(40), mode=mode)
    for a, b in zip(
        (full.init, full.trans, full.emit, full.loglik),
        (part.init, part.trans, part.emit, part.loglik),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.parametrize("mode", ["log", "rescaled"])
def test_zero_length_chunk_contributes_nothing(mode, rng):
    pi, A, B = _random_model(rng)
    params = HmmParams.from_probs(pi, A, B)
    empty = jnp.full(16, 4, dtype=jnp.int32)
    st = FB.chunk_stats(params, empty, jnp.int32(0), mode=mode)
    assert float(jnp.sum(st.init)) == 0.0
    assert float(jnp.sum(st.trans)) == 0.0
    assert float(jnp.sum(st.emit)) == 0.0
    assert float(st.loglik) == 0.0
    assert int(st.n_seqs) == 0


def test_log_vs_rescaled_agree(rng):
    pi, A, B = _random_model(rng, k=4)
    params = HmmParams.from_probs(pi, A, B)
    obs = jnp.asarray(rng.integers(0, 4, size=256))
    a = FB.chunk_stats(params, obs, jnp.int32(256), mode="log")
    b = FB.chunk_stats(params, obs, jnp.int32(256), mode="rescaled")
    np.testing.assert_allclose(np.asarray(a.trans), np.asarray(b.trans), rtol=1e-3, atol=1e-2)
    np.testing.assert_allclose(np.asarray(a.emit), np.asarray(b.emit), rtol=1e-3, atol=1e-2)
    assert float(a.loglik) == pytest.approx(float(b.loglik), rel=1e-4)


def test_batch_stats_sums_chunks(rng):
    pi, A, B = _random_model(rng)
    params = HmmParams.from_probs(pi, A, B)
    chunks = rng.integers(0, 4, size=(6, 32)).astype(np.int32)
    lengths = np.full(6, 32, dtype=np.int32)
    batched = FB.batch_stats(params, jnp.asarray(chunks), jnp.asarray(lengths))
    total = FB.SuffStats.zeros(3, 4)
    for i in range(6):
        total = total + FB.chunk_stats(params, jnp.asarray(chunks[i]), jnp.int32(32))
    np.testing.assert_allclose(np.asarray(batched.trans), np.asarray(total.trans), atol=1e-3)
    assert int(batched.n_seqs) == 6


def test_one_hot_emissions_are_fixed_point(rng):
    """Structural zeros must accumulate exactly zero count (SURVEY.md C5)."""
    from cpgisland_tpu.models import presets

    params = presets.durbin_cpg8()
    obs = jnp.asarray(rng.integers(0, 4, size=128))
    st = FB.chunk_stats(params, obs, jnp.int32(128))
    emit = np.asarray(st.emit)
    B = np.asarray(params.B)
    assert (emit[B == 0] == 0).all()


def test_posterior_marginals_match_oracle(rng):
    from cpgisland_tpu.ops.forward_backward import posterior_decode, posterior_marginals

    pi = rng.dirichlet(np.ones(3))
    A = rng.dirichlet(np.ones(3), size=3)
    B = rng.dirichlet(np.ones(4), size=3)
    params = HmmParams.from_probs(pi, A, B)
    obs = rng.integers(0, 4, size=400).astype(np.uint8)
    gamma_o, _, ll_o = oracle.forward_backward_oracle(pi, A, B, obs)
    gamma, ll = posterior_marginals(params, jnp.asarray(obs))
    # 1e-4: covers TPU's ~2e-5-relative exp/log approximation
    np.testing.assert_allclose(np.asarray(gamma), gamma_o, atol=1e-4)
    # abs 2e-2: the same TPU-numerics bound the chunk-stats loglik check uses
    assert float(ll) == pytest.approx(ll_o, abs=2e-2)
    path = np.asarray(posterior_decode(params, jnp.asarray(obs)))
    # consistency contract: the decode is the argmax of the DEVICE gamma
    # (oracle argmax could differ at positions with sub-tolerance margins)
    np.testing.assert_array_equal(path, np.argmax(np.asarray(gamma), axis=1))


def test_sample_sequence_statistics(rng):
    import jax

    from cpgisland_tpu.models.hmm import sample_sequence
    from cpgisland_tpu.models import presets

    params = presets.durbin_cpg8()
    states, obs = sample_sequence(params, jax.random.PRNGKey(0), 50000)
    assert states.shape == obs.shape == (50000,)
    # one-hot emissions: observation == state % 4 always
    np.testing.assert_array_equal(np.asarray(obs), np.asarray(states) % 4)
    # empirical transition rows approximate A for visited states
    s = np.asarray(states)
    A = np.asarray(params.A)
    for i in range(8):
        idx = np.flatnonzero(s[:-1] == i)
        if idx.size > 1000:
            emp = np.bincount(s[idx + 1], minlength=8) / idx.size
            np.testing.assert_allclose(emp, A[i], atol=0.05)


def test_posterior_marginals_padded_tail(rng):
    """length masks a padded tail: gamma rows beyond it are 0 and the valid
    prefix matches the unpadded computation."""
    from cpgisland_tpu.ops.forward_backward import posterior_marginals

    pi = rng.dirichlet(np.ones(3))
    A = rng.dirichlet(np.ones(3), size=3)
    B = rng.dirichlet(np.ones(4), size=3)
    params = HmmParams.from_probs(pi, A, B)
    obs = rng.integers(0, 4, size=300).astype(np.uint8)
    padded = np.concatenate([obs, np.full(50, 4, np.uint8)])  # PAD sentinel tail
    g_plain, ll_plain = posterior_marginals(params, jnp.asarray(obs))
    g_pad, ll_pad = posterior_marginals(params, jnp.asarray(padded), length=300)
    np.testing.assert_allclose(np.asarray(g_pad[:300]), np.asarray(g_plain), atol=1e-6)
    np.testing.assert_array_equal(np.asarray(g_pad[300:]), 0.0)
    assert float(ll_pad) == pytest.approx(float(ll_plain), abs=1e-3)
