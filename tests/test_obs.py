"""Runtime telemetry subsystem (obs/): spans, ledger, sentinel, watchdog,
engine-decision events, report tooling."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from cpgisland_tpu import obs, pipeline
from cpgisland_tpu.models import presets
from cpgisland_tpu.obs import ledger as ledger_mod
from cpgisland_tpu.obs import report as report_mod
from cpgisland_tpu.obs import watchdog as watchdog_mod
from cpgisland_tpu.train import baum_welch
from cpgisland_tpu.utils import chunking, codec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_fasta(path, rng, n=4096):
    path.write_text(">t\n" + codec.decode_symbols(rng.integers(0, 4, size=n)) + "\n")
    return str(path)


# ---------------------------------------------------------------------------
# off-by-default contract


def test_disabled_helpers_are_noops():
    assert obs.current() is None and not obs.enabled()
    with obs.span("nothing", items=5, unit="sym") as sp:
        assert sp is None
    obs.event("anything", x=1)
    obs.engine_decision(site="s", choice="c")
    arr = np.ones(4)
    assert obs.note_fetch(arr) is arr
    assert obs.note_upload(arr) is arr


def test_disabled_leaves_jax_unpatched():
    orig_block = jax.block_until_ready
    orig_put = jax.device_put
    with obs.observe():
        assert jax.block_until_ready is not orig_block
        assert jax.device_put is not orig_put
    # exiting restores the original functions exactly
    assert jax.block_until_ready is orig_block
    assert jax.device_put is orig_put


def test_no_observer_nesting():
    with obs.observe():
        with pytest.raises(RuntimeError, match="already active"):
            obs.Observer().__enter__()


# ---------------------------------------------------------------------------
# spans + chrome trace


def test_spans_nest_and_chrome_trace_validates(tmp_path):
    mpath = tmp_path / "m.jsonl"
    with obs.observe(metrics=str(mpath), trace_dir=str(tmp_path)) as ob:
        with obs.span("outer", items=10, unit="sym"):
            with obs.span("inner", items=4, unit="sym", extra="x"):
                pass
        assert [s.name for s in ob.tracer.spans] == ["inner", "outer"]

    # JSONL span events carry hierarchy + process index
    recs = [json.loads(ln) for ln in mpath.read_text().splitlines()]
    spans = {r["name"]: r for r in recs if r["event"] == "span"}
    assert spans["inner"]["depth"] == 1 and spans["outer"]["depth"] == 0
    assert spans["inner"]["parent_id"] == spans["outer"]["span_id"]
    assert all("process_index" in r for r in recs)
    assert recs[-1]["event"] == "obs_summary"

    # Chrome trace parses, has ph/ts/pid, and the child nests inside the
    # parent's [ts, ts+dur] window.
    tr = json.load(open(tmp_path / "trace.json"))
    evs = [e for e in tr["traceEvents"] if e["ph"] == "X"]
    assert evs and all({"ph", "ts", "dur", "pid", "name"} <= set(e) for e in evs)
    by = {e["name"]: e for e in evs}
    inner, outer = by["inner"], by["outer"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3


def test_span_counters_attribute_compiles_and_dispatches():
    import jax.numpy as jnp

    with obs.observe() as ob:
        with obs.span("work"):
            x = jax.jit(lambda v: v * 3 + 1)(jnp.arange(7))
            jax.block_until_ready(x)
            jax.device_get(x)
    sp = ob.tracer.spans[0]
    assert sp.counters["compiles"] >= 1
    assert sp.counters["dispatches"] >= 2  # block + get
    assert sp.counters["fetch_bytes"] >= x.nbytes
    # eager helper compiles (jit_iota for arange) are recorded too; the
    # jitted lambda's record carries its abstract input types
    recs = ob.ledger.compile_records
    assert any(
        r["name"].startswith("jit_") and r["arg_types"] for r in recs
    )


# ---------------------------------------------------------------------------
# recompile sentinel


def test_sentinel_steady_state_em_zero_recompiles(rng):
    """>= 2 steady-state fit iterations over fixed shapes trigger ZERO fresh
    compiles after iteration 1 (the warm run)."""
    syms = rng.integers(0, 4, size=4096).astype(np.uint8)
    ck = chunking.frame(syms, 256)
    # fuse=False: this certifies the HOST-loop cadence (the fused loop has
    # its own sentinel test in tests/test_baum_welch.py — its compiled
    # program is keyed on num_iters, so a 1-iter warm run would not warm it).
    warm = baum_welch.fit(
        presets.durbin_cpg8(), ck, num_iters=1, convergence=0.0, fuse=False
    )
    with obs.no_new_compiles("steady-em") as led:
        res = baum_welch.fit(
            warm.params, ck, num_iters=2, convergence=0.0, fuse=False
        )
    assert res.iterations == 2
    assert led.compiles == 0


def test_sentinel_fires_on_shape_change(rng):
    syms = rng.integers(0, 4, size=4096).astype(np.uint8)
    warm = baum_welch.fit(
        presets.durbin_cpg8(), chunking.frame(syms, 256), num_iters=1,
        convergence=0.0,
    )
    with pytest.raises(ledger_mod.RecompileError, match="fresh XLA compile"):
        with obs.no_new_compiles("shape-change"):
            baum_welch.fit(
                warm.params, chunking.frame(syms, 512), num_iters=1,
                convergence=0.0,
            )
    # the hooks are gone again: a fresh-shape compile outside raises nothing
    import jax.numpy as jnp

    jax.jit(lambda v: v + 2)(jnp.arange(3))


def test_sentinel_records_name_and_shapes(rng):
    import jax.numpy as jnp

    try:
        with obs.no_new_compiles("probe"):
            jax.jit(lambda v: v * 5)(jnp.arange(11))
        raise AssertionError("sentinel did not fire")
    except ledger_mod.RecompileError as e:
        assert e.records
        assert any("tensor<" in "".join(r["arg_types"]) for r in e.records)


# ---------------------------------------------------------------------------
# watchdog


def test_watchdog_regex_matches_pubnum():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import pubnum
    finally:
        sys.path.pop(0)
    assert pubnum._NUM_RE.pattern == watchdog_mod.NUM_RE.pattern


def test_watchdog_ceilings_from_baseline():
    ceils = watchdog_mod.path_ceilings()
    assert "decode" in ceils and "posterior" in ceils and "em" in ceils
    nums = watchdog_mod.baseline_numbers()
    assert ceils["decode"] == pytest.approx(2.5 * nums["decode_msym"] * 1e6)


def test_watchdog_modes():
    wd = watchdog_mod.Watchdog(mode="warn")
    # plausible: well under any ceiling
    assert wd.check("decode", items=1e6, seconds=1.0) is None
    # phantom-grade: far over the decode ceiling
    v = wd.check("decode", items=1e12, seconds=0.01)
    assert v is not None and wd.violations == [v]
    with pytest.raises(watchdog_mod.ImplausibleThroughput):
        watchdog_mod.Watchdog(mode="raise").check("decode", items=1e12, seconds=0.01)
    assert watchdog_mod.Watchdog(mode="off").check("decode", 1e12, 0.01) is None


def test_watchdog_flags_phantom_span(tmp_path):
    """An instrumented span whose wall is phantom-fast is flagged in the
    metrics stream (the library generalization of bench._check_plausible)."""
    mpath = tmp_path / "m.jsonl"
    with obs.observe(metrics=str(mpath)) as ob:
        with ob.tracer.span("decode", items=1e12, unit="sym"):
            pass  # ~0 wall => absurd Msym/s
    assert ob.watchdog.violations
    summary = [
        json.loads(ln) for ln in mpath.read_text().splitlines()
    ][-1]
    assert summary["watchdog_violations"]


# ---------------------------------------------------------------------------
# engine-decision events through the real pipelines (virtual mesh)


def test_pipeline_decode_and_posterior_emit_events(tmp_path, rng):
    fa = _write_fasta(tmp_path / "g.fa", rng)
    mpath = tmp_path / "m.jsonl"
    with obs.observe(metrics=str(mpath), trace_dir=str(tmp_path)) as ob:
        pipeline.decode_file(
            fa, presets.durbin_cpg8(), compat=False, metrics=ob.metrics
        )
        pipeline.posterior_file(
            fa, presets.durbin_cpg8(), islands_out=str(tmp_path / "i.txt"),
            metrics=ob.metrics,
        )
    recs = [json.loads(ln) for ln in mpath.read_text().splitlines()]
    decisions = [r for r in recs if r["event"] == "engine_decision"]
    sites = {r["site"]: r["choice"] for r in decisions}
    # On the CPU virtual mesh auto resolves to the XLA lowerings everywhere.
    assert sites["decode.resolve_engine"] == "xla"
    assert sites["posterior.resolve_fb_engine"] == "xla"
    assert sites["island_engine"] == "host"
    span_names = {r["name"] for r in recs if r["event"] == "span"}
    assert {"decode", "islands", "posterior"} <= span_names
    # the chrome trace covers the pipeline spans too
    tr = json.load(open(tmp_path / "trace.json"))
    assert {"decode", "posterior"} <= {
        e["name"] for e in tr["traceEvents"] if e["ph"] == "X"
    }


def test_seq_shard_budget_reject_event(rng):
    from cpgisland_tpu.train import backends

    with obs.observe() as ob:
        with pytest.raises(ValueError, match="budget"):
            backends._check_seq_shard(backends.SEQ_SHARD_BUDGET + 1, "SeqBackend")
    assert any(e["event"] == "seq_shard_budget_reject" for e in ob.events)


def test_fit_emits_em_iter_spans(rng):
    syms = rng.integers(0, 4, size=2048).astype(np.uint8)
    ck = chunking.frame(syms, 256)
    with obs.observe() as ob:
        baum_welch.fit(
            presets.durbin_cpg8(), ck, num_iters=2, convergence=0.0,
            fuse=False,  # per-iteration spans are the host-loop cadence
        )
    iters = [s for s in ob.tracer.spans if s.name == "em_iter"]
    assert len(iters) == 2
    assert iters[0].items == float(ck.total)
    assert iters[0].attrs["iteration"] == 1
    # The fused loop emits ONE em_fused span covering all iterations.
    with obs.observe() as ob:
        baum_welch.fit(presets.durbin_cpg8(), ck, num_iters=2, convergence=0.0)
    fused = [s for s in ob.tracer.spans if s.name == "em_fused"]
    assert len(fused) == 1
    assert fused[0].items == 2.0 * ck.total
    assert not any(s.name == "em_iter" for s in ob.tracer.spans)


# ---------------------------------------------------------------------------
# report tooling


def test_obs_report_reconstructs_run(tmp_path, rng):
    """Acceptance: from the JSONL alone, tools/obs_report.py reconstructs
    phase walls, compile count, dispatch count, and the engine per phase."""
    fa = _write_fasta(tmp_path / "g.fa", rng)
    mpath = tmp_path / "m.jsonl"
    with obs.observe(metrics=str(mpath)) as ob:
        pipeline.posterior_file(
            fa, presets.durbin_cpg8(), islands_out=str(tmp_path / "i.txt"),
            metrics=ob.metrics,
        )
    summary = report_mod.summarize_jsonl(str(mpath))
    assert summary["spans"]["posterior"]["wall_s"] > 0
    assert summary["spans"]["posterior"]["count"] >= 1
    ledger = summary["ledger"]
    # compile count is reconstructable (0 when a prior test warmed the
    # in-process caches — the count is still the truth for THIS region)
    assert isinstance(ledger["compiles"], int)
    assert ledger["dispatches"] >= 1
    assert any(
        "posterior.resolve_fb_engine" in label and "choice=xla" in label
        for label in summary["decisions"]
    )
    text = report_mod.render_file(str(mpath))
    assert "posterior" in text and "ledger totals" in text

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"), str(mpath)],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr[-500:]
    assert "posterior" in out.stdout and "compiles=" in out.stdout


def test_cli_obs_flags(tmp_path, rng):
    from cpgisland_tpu import cli

    fa = _write_fasta(tmp_path / "g.fa", rng, n=2048)
    mpath = tmp_path / "m.jsonl"
    rc = cli.main([
        "decode", fa, "--clean", "--islands-out", str(tmp_path / "i.txt"),
        "--metrics", str(mpath), "--obs-report",
    ])
    assert rc == 0
    recs = [json.loads(ln) for ln in mpath.read_text().splitlines()]
    assert any(r["event"] == "span" and r["name"] == "decode" for r in recs)
    assert recs[-1]["event"] == "obs_summary"


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_bench_metrics_sidecar_smoke(tmp_path):
    """bench.py --metrics-out writes the telemetry sidecar while stdout stays
    ONE JSON line (tiny CPU config; tier-1-safe)."""
    side = tmp_path / "bench.jsonl"
    out = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "bench.py"),
            "--phase", "core", "--platform", "cpu",
            "--decode-mib", "1", "--em-chunks", "4",
            "--metrics-out", str(side),
        ],
        capture_output=True, text=True, timeout=1200, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-800:]
    stdout_lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    assert len(stdout_lines) == 1
    assert "decode_tput" in json.loads(stdout_lines[0])
    recs = [json.loads(ln) for ln in side.read_text().splitlines()]
    assert any(r["event"] == "bench_phase" for r in recs)
    assert recs[-1]["event"] == "obs_summary"
    assert recs[-1]["ledger"]["compiles"] >= 1
