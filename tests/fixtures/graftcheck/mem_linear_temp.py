"""Planted regression: a WHOLE-RECORD O(T) island temp.

The same run accounting as ``mem_clean``, computed without blocking: the
int8 path upcasts to a full s32[T] stream and a record-length cumsum
materializes beside it — live allocations that scale with T instead of
the block width (the ~15 GB s32[T] OOM class the blocked island
reduction was built to kill).  Must be caught by (a) the lockfile diff
(the O(T) allocation-group list drifts, new group NAMED) and (b) the
liveness detector directly (linear_alloc_groups slope >= the s32
4 B/symbol class).
"""

from mem_clean import BASE_SYMBOLS, _path  # noqa: F401


def make(scale: int = 1):
    import jax.numpy as jnp

    path = _path(scale)

    def fn(p):
        b = p.astype(jnp.int32)                    # s32[T] temp
        in_mask = b < 3
        runs = jnp.cumsum(in_mask.astype(jnp.int32))   # another s32[T]
        anchored = jnp.maximum(runs, b)            # and a third
        return anchored[-1], jnp.max(runs)

    return fn, (path,)
