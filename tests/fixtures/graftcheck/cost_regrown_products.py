"""Planted regression: a REGROWN standalone PRODUCTS pass.

The ISSUE 17 one-pass collapse folds the reduced paths' [2,2]
transfer-matrix products into the co-scheduled fwd/bwd launch (the
matrix-carried kernel emits per-lane transfer totals itself), so the
standalone products/boundary pass disappears (posterior/em-seq dropped
2 -> 1 T-scaling passes).  This twin models the regression the fold
exists to prevent: the same work as ``cost_clean`` (one max-plus chain +
epilogue) plus a SECOND independent forward T-trip scan COMPOSING the
per-step [2,2] matrices — the de-folded products pass re-materializing
as its own launch.  Must be caught by (a) the lockfile diff (scan eqn
count + serial depth, scan named) and (b) the pass-structure pin
(passes 1 -> 2 vs the clean baseline).
"""

from cost_clean import BASE_SYMBOLS, _chain, _epilogue, _steps  # noqa: F401


def make(scale: int = 1):
    import jax
    import jax.numpy as jnp
    import numpy as np

    obs = jnp.asarray(np.arange(BASE_SYMBOLS * scale, dtype=np.int32) % 4)

    def fn(o):
        steps = _steps(o)
        carry, ys = _chain(steps)

        # The regrown pass: an INDEPENDENT forward products scan over the
        # same steps — per-step [2, 2] matrix composition with deferred
        # renorm, exactly the standalone boundary-products shape the
        # matrix-carried kernel absorbed.  Its own scan eqn, its own
        # T-scaling serial chain.
        def products(m, step):
            new = step @ m
            new = new / jnp.maximum(jnp.max(new), 1e-30)
            return new, new[0, 0]

        m2, ys2 = jax.lax.scan(products, jnp.eye(2, dtype=jnp.float32), steps)
        return carry.sum() + ys.sum() + m2.sum() + ys2.sum() + _epilogue()

    return fn, (obs,)
