"""Planted regression: a stacked-M VMEM overflow.

PR 12's stacked kernels scale VMEM with member count M ('the score
variant's per-member dmax rows scale the kernel working set by M',
viterbi_onehot) and shipped with no static guard: three members' score
rows at the flat default bk=4096 overflow the 16 MiB model.  The test
asserts memmodel.feasible rejects the tuple NAMING the per-member dmax
buffer, and that the guard's derived block cap restores feasibility.
"""

from cpgisland_tpu.analysis import memmodel

KERNEL = "decode.backpointers.onehot.scores"
KNOBS = memmodel.Knobs(block_size=4096, stacked_m=3)
