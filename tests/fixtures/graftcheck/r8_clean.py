"""trace-time-consult clean: consultation resolved HOST-side, the knob
passed explicitly; in-trace fallbacks use the pure legacy heuristic."""

import jax

from cpgisland_tpu.ops import fb_pallas


def make_stats_fn(lane_T):
    def body(params, obs_tile):
        # The knob arrives resolved; the in-trace fallback is the PURE
        # rate-table heuristic (no winner-table lookup, no freeze).
        lt = lane_T if lane_T is not None else fb_pallas.legacy_lane_T(
            obs_tile.shape[1], onehot=True)
        return obs_tile.reshape(lt, -1).sum()

    return body


def run(mesh, params, obs):
    # Consult where it belongs: on the host, before the trace.
    lane_T = fb_pallas.pick_lane_T(obs.shape[1], onehot=True)
    body = make_stats_fn(lane_T)
    return jax.jit(jax.shard_map(
        body, mesh, in_specs=None, out_specs=None))(params, obs)
