"""Planted regression: grown fixed-cost (size-independent) epilogue.

Identical to ``cost_clean`` except the model-sized 8x8 epilogue became a
256x256 matmul — ~33 MFLOP of FIXED cost per invocation, invisible to
any per-symbol throughput figure but exactly what the size curve pins
(the ~8-11 ms class of regression).  Must be caught by the lockfile diff
as ``flops.fixed`` drift with ``dot_general`` named.
"""

from cost_clean import BASE_SYMBOLS, _chain, _epilogue, _steps  # noqa: F401


def make(scale: int = 1):
    import jax.numpy as jnp
    import numpy as np

    obs = jnp.asarray(np.arange(BASE_SYMBOLS * scale, dtype=np.int32) % 4)

    def fn(o):
        carry, ys = _chain(_steps(o))
        return carry.sum() + ys.sum() + _epilogue(256)

    return fn, (obs,)
