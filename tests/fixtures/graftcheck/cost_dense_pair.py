"""Planted regression: an O(T·S²) dense-pair op on the reduced path.

Identical to ``cost_clean`` except a [T, 8, 8] dense pair tensor is
materialized and folded in — the exact shape of a reintroduced dense
xi/products op (64 result elements per symbol, vs the reduced stream's 4).
Must be caught by (a) the lockfile diff (flops/bytes drift naming the new
primitives) and (b) the ``cost.reduced-no-dense-pair`` contract.
"""

from cost_clean import BASE_SYMBOLS, _chain, _epilogue, _steps  # noqa: F401


def make(scale: int = 1):
    import jax.numpy as jnp
    import numpy as np

    obs = jnp.asarray(np.arange(BASE_SYMBOLS * scale, dtype=np.int32) % 4)

    def fn(o):
        carry, ys = _chain(_steps(o))
        # The planted dense pair tensor: [T, S, S] with S=8.
        dense = jnp.ones((o.shape[0], 8, 8), jnp.float32) * (
            o[:, None, None].astype(jnp.float32)
        )
        xi = jnp.einsum("tij,tjk->tik", dense, dense)
        return carry.sum() + ys.sum() + xi.sum() + _epilogue()

    return fn, (obs,)
