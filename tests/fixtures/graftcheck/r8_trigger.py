"""trace-time-consult trigger: graftune consultation reachable from
traced bodies — the winner freezes into the jit cache at trace time, so
an applied sweep never takes effect for already-compiled programs."""

import jax

from cpgisland_tpu import tune
from cpgisland_tpu.ops import fb_pallas


@jax.jit
def stats(obs):
    # Direct consult inside a jit target.
    lt = fb_pallas.pick_lane_T(obs.shape[0], onehot=True)
    return obs.reshape(lt, -1).sum(axis=0)


def make_stats_fn(mesh):
    def body(params, obs_tile):
        # The fb_sharded pattern: the def is returned and jitted by a
        # SIBLING function — only name-based matching sees it.
        lane = tune.tuned_lane_T(obs_tile.shape[1], onehot=True)
        return obs_tile.reshape(lane or 8192, -1).sum()

    return body


def run(mesh, params, obs):
    body = make_stats_fn(mesh)
    return jax.jit(jax.shard_map(
        body, mesh, in_specs=None, out_specs=None))(params, obs)


def scan_driver(xs):
    def step(carry, x):
        bs = tune.default_block_size("decode.flat", 4096)
        return carry + x[:bs].sum(), None

    return jax.lax.scan(step, 0.0, xs)
