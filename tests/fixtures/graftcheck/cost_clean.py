"""Planted-regression twin set for the Layer-3 cost lockfile (graftcost).

``cost_clean`` is the baseline: a miniature reduced-path program — a
[T, 2, 2] pair-step stream driven through a sequential max-plus scan plus
a model-sized epilogue — mirroring the shape of the real reduced engines.
Each ``cost_*`` sibling plants exactly ONE of the regressions the lockfile
diff exists to catch (dense pair op, doubled scan depth, grown fixed
epilogue, f64 upcast).  tests/test_graftcheck_self.py baselines the clean
twin and asserts every planted twin fails the diff with the drifting
primitives named.

Fixture contract: ``make(scale)`` returns ``(fn, (args,))`` with the time
geometry multiplied by ``scale``; ``BASE_SYMBOLS`` is the scale-1 symbol
count (the same shape ``analysis.contracts.Contract.make`` has).
"""

BASE_SYMBOLS = 1024


def _steps(o):
    import jax.numpy as jnp

    # Reduced pair-step stream: [T, 2, 2], 4 elements per symbol.
    return jnp.ones((o.shape[0], 2, 2), jnp.float32) * (
        o[:, None, None].astype(jnp.float32)
    )


def _chain(steps):
    import jax
    import jax.numpy as jnp

    def body(carry, step):
        new = jnp.max(step + carry[None, :], axis=1)
        return new, new[0]

    return jax.lax.scan(body, jnp.zeros(2, jnp.float32), steps)


def _epilogue(n: int = 8):
    import jax.numpy as jnp

    m = jnp.eye(n, dtype=jnp.float32)
    return (m @ m).sum()


def make(scale: int = 1):
    import jax.numpy as jnp
    import numpy as np

    obs = jnp.asarray(np.arange(BASE_SYMBOLS * scale, dtype=np.int32) % 4)

    def fn(o):
        carry, ys = _chain(_steps(o))
        return carry.sum() + ys.sum() + _epilogue()

    return fn, (obs,)
