"""Planted regression: a REGROWN third sequential pass.

The r9 pass-count collapse fused the reduced paths' forward and backward
chains into ONE co-scheduled scan (posterior/em-seq dropped 3 -> 2
T-scaling passes, chunked EM 2 -> 1).  This twin models the regression
that fusion exists to prevent: the same work as ``cost_clean`` (one
max-plus chain + epilogue) plus a SECOND independent T-trip scan over the
same steps — a de-fused backward re-materializing as its own pass.  Must
be caught by (a) the lockfile diff (scan eqn count + serial depth, scan
named) and (b) the pass-structure pin (passes 1 -> 2 vs the clean
baseline).
"""

from cost_clean import BASE_SYMBOLS, _chain, _epilogue, _steps  # noqa: F401


def make(scale: int = 1):
    import jax
    import jax.numpy as jnp
    import numpy as np

    obs = jnp.asarray(np.arange(BASE_SYMBOLS * scale, dtype=np.int32) % 4)

    def fn(o):
        steps = _steps(o)
        carry, ys = _chain(steps)

        # The regrown pass: an INDEPENDENT second chain over the same
        # steps (reversed — the de-fused backward), its own scan eqn.
        def bwd(c, step):
            new = jnp.max(step + c[None, :], axis=1)
            return new, new[1]

        carry2, ys2 = jax.lax.scan(
            bwd, jnp.zeros(2, jnp.float32), steps, reverse=True
        )
        return carry.sum() + ys.sum() + carry2.sum() + ys2.sum() + _epilogue()

    return fn, (obs,)
