"""hot-path-host-sync clean: syncs routed through obs.note_fetch, host
inputs coerced at the boundary."""

import jax.numpy as jnp
import numpy as np

from cpgisland_tpu import obs


# graftcheck: hot-path
def decode_loop(params, spans):
    obs_arr = np.asarray(spans)  # param-rooted: host input coercion
    totals = []
    for s in obs_arr:
        total_dev = jnp.dot(s, params)
        totals.append(obs.note_fetch(np.asarray(total_dev)))
    return totals


def not_registered(x):
    # Outside a hot path the rule does not apply at all.
    return np.asarray(x)
