"""Planted-regression twin set for the Layer-5 memory lockfile (graftmem).

``mem_clean`` is the baseline: a miniature BLOCKED reduction in the shape
of the on-device island caller (ops/islands_device.py) — the [T] input
reshapes to [nB, W] blocks and ONE ``lax.scan`` threads fixed-size carry
state across them, so every materialized temporary is O(W), never O(T).
``mem_linear_temp`` is the regression twin: the same accounting computed
WHOLE-RECORD, materializing s32[T] temps — the formulation whose real
ancestor OOMed ~15 GB at 320 Mi symbols (CLAUDE.md r4).
tests/test_graftcheck_self.py baselines the clean twin and asserts the
planted twin fails the liveness diff with the offending allocation group
NAMED.

Fixture contract: ``make(scale)`` returns ``(fn, (args,))`` with the time
geometry multiplied by ``scale``; ``BASE_SYMBOLS`` is the scale-1 symbol
count (the same shape ``analysis.contracts.Contract.make`` has).
"""

BASE_SYMBOLS = 32768
BLOCK_W = 4096


def _path(scale: int):
    import jax.numpy as jnp
    import numpy as np

    return jnp.asarray(
        (np.arange(BASE_SYMBOLS * scale, dtype=np.int64) % 7).astype(np.int8)
    )


def make(scale: int = 1):
    import jax
    import jax.numpy as jnp

    path = _path(scale)
    T = path.shape[0]
    nB = T // BLOCK_W

    def fn(p):
        blocks = p.reshape(nB, BLOCK_W)

        def body(carry, blk):
            b = blk.astype(jnp.int32)          # O(W) temp, per block
            in_mask = b < 3
            runs = jnp.cumsum(in_mask.astype(jnp.int32))  # O(W)
            carry = carry + runs[-1]
            return carry, jnp.max(runs)

        total, per_block = jax.lax.scan(body, jnp.int32(0), blocks)
        return total, per_block.sum()

    return fn, (path,)
