"""pallas-sublane-align trigger: the exact anti-patterns from CLAUDE.md."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

ROW_TILE = 8


def _bad_kernel(steps_ref, tab_ref, out_ref, *, Tt):
    def body(i, v):
        # The canonical bad form: Tt - 8 - i*8 is not provably 8-aligned.
        tile = steps_ref[pl.ds(Tt - 8 - i * 8, ROW_TILE), :]
        # Rank-3 value inside a kernel.
        cube = jnp.reshape(tile, (2, 4, tile.shape[1]))
        # [1,1] table load broadcast inside the kernel.
        t = jnp.broadcast_to(tab_ref[0, 0], (8, 128))
        out_ref[pl.ds(i * ROW_TILE, ROW_TILE), :] = tile + t + cube[0]
        return v

    jax.lax.fori_loop(0, Tt // ROW_TILE, body, 0)
