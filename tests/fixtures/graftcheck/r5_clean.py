"""no-stats-in-bwd-chain clean: the backward walk only emits per-position
values (the _bwd_conf_kernel pattern); reduction happens in a separate
pass off the recurrence chain."""

import jax
import jax.numpy as jnp


def backward_emit(A, emits, beta_T, mask):
    def bstep(beta_next, b_next):
        beta_t = jnp.matmul(A, b_next * beta_next)
        conf_t = jnp.sum(beta_t * mask)  # light per-position emission
        return beta_t, conf_t

    beta_0, confs = jax.lax.scan(bstep, beta_T, emits, reverse=True)
    return beta_0, jnp.sum(confs)  # the reduction lives OUTSIDE the chain
