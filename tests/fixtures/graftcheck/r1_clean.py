"""jit-big-closure clean: arrays traced as arguments; small literal tables
are exempt (the lane-broadcast constants kernels legitimately bake)."""

import jax
import jax.numpy as jnp

IDENT4 = jnp.asarray([1.0, 0.0, 0.0, 1.0])  # <= 64 literal elements: fine


@jax.jit
def apply_table(x, table):
    return x + table + IDENT4[0]


def make_fn(table):
    # Closing over a function PARAMETER is the factory pattern, not a baked
    # module constant — the caller controls what ships.
    return jax.jit(lambda x: table[x])
