"""Planted regression: f64 upcast on the device path.

Identical to ``cost_clean`` except the pair-step stream is upcast to
float64 — doubling every stream byte (the lockfile diff's ``bytes``
drift names ``convert_element_type``) and tripping the boolean layer's
no-f64 contract (``contracts.inspect_jaxpr``).
"""

from cost_clean import BASE_SYMBOLS, _chain, _epilogue, _steps  # noqa: F401


def make(scale: int = 1):
    import jax.numpy as jnp
    import numpy as np

    obs = jnp.asarray(np.arange(BASE_SYMBOLS * scale, dtype=np.int32) % 4)

    def fn(o):
        import jax

        def body(carry, step):
            new = jnp.max(step + carry[None, :], axis=1)
            return new, new[0]

        steps64 = _steps(o).astype(jnp.float64)
        carry, ys = jax.lax.scan(body, jnp.zeros(2, jnp.float64), steps64)
        return (carry.sum() + ys.sum()).astype(jnp.float32) + _epilogue()

    return fn, (obs,)
