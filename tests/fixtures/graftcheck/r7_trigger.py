"""jit-const-capture trigger: a big host-numpy constant built INSIDE a
traced body becomes a jaxpr constvar baked into the compiled module (the
HTTP 413 remote-compile cliff) — R1 can't see it, it isn't a closure."""

import numpy as np
import jax
import jax.numpy as jnp


@jax.jit
def score(obs):
    # 64 Mi float64 = 512 MiB baked constant, way past the budget.
    table = np.zeros((8192, 8192))
    return jnp.asarray(table)[obs]


def make_body():
    def body(carry, x):
        # Estimable via the 1<<k shift form too.
        offsets = np.arange(1 << 26)
        return carry, jnp.asarray(offsets)[x]

    return jax.jit(lambda c, x: jax.lax.scan(body, c, x))
