"""pallas-sublane-align clean: aligned offsets, rank-2 values, tables
lane-broadcast outside the kernel."""

import jax
from jax.experimental import pallas as pl

ROW_TILE = 8
OUTER_TILE = 64


def _good_kernel(steps_ref, tab_ref, out_ref, *, Tt, bk):
    def body(i, v):
        base = i * ROW_TILE
        tile = steps_ref[pl.ds(base, ROW_TILE), :]
        row = tab_ref[0:1, :]  # [1, LT] row of a pre-broadcast table
        out_ref[pl.ds(i * OUTER_TILE + 0 * ROW_TILE, ROW_TILE), :] = tile + row
        return v

    jax.lax.fori_loop(0, Tt // ROW_TILE, body, 0)
