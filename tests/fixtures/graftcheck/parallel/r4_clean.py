"""maxplus-normalize clean: every combine flows straight through
nrm_maxplus."""

import jax

from cpgisland_tpu.ops.viterbi_parallel import maxplus_matmul, nrm_maxplus


def stitch(totals, eye):
    def fwd(carry, t):
        return nrm_maxplus(maxplus_matmul(carry, t)), carry

    return jax.lax.scan(fwd, eye, totals)
