"""maxplus-normalize trigger: an unnormalized max-plus combine chain in a
parallel/ module (fixture mirrors the stitching-layer layout)."""

import jax
import jax.numpy as jnp

from cpgisland_tpu.ops.viterbi_parallel import maxplus_matmul, nrm_maxplus


def stitch(totals, eye):
    def fwd(carry, t):
        return maxplus_matmul(carry, t), carry  # drifts ~-1.3 nat/symbol

    return jax.lax.scan(fwd, eye, totals)
