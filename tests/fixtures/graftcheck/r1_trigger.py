"""jit-big-closure trigger: jitted functions closing over array constants."""

import jax
import jax.numpy as jnp
import numpy as np

BIG_TABLE = np.zeros((1024, 1024), np.float32)  # module-scope baked constant


@jax.jit
def apply_table(x):
    return x + BIG_TABLE


def make_fn():
    lut = jnp.arange(65536)  # enclosing-scope array constant
    return jax.jit(lambda x: lut[x])
