"""Waiver-parsing fixture: one properly waived violation (inline and
standalone-comment forms), one waiver missing its reason, one stale waiver
covering nothing."""

import jax
import numpy as np

BAKED = np.zeros((64, 64), np.float32)


@jax.jit
def waived_inline(x):
    return x + BAKED  # graftcheck: allow(jit-big-closure) -- test-only 16 KiB table; the fixture exists to prove waivers parse


@jax.jit
def waived_standalone(x):
    # graftcheck: allow(jit-big-closure) -- standalone-comment form covers the next line
    return x + BAKED


@jax.jit
def missing_reason(x):
    return x + BAKED  # graftcheck: allow(jit-big-closure)


def stale():
    # graftcheck: allow(maxplus-normalize) -- nothing here triggers it
    return 0
