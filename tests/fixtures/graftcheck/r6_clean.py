"""retrace-hazard clean: Python scalars declared static."""

from functools import partial

import jax


@partial(jax.jit, static_argnames=("block_size",))
def decode(obs, block_size: int = 4096):
    return obs.reshape(-1, block_size)


def windowed(obs, width: int):
    return obs[:width]


windowed_jit = jax.jit(windowed, static_argnames=("width",))
