"""Hygiene trigger: unused import + builtin shadowing."""

import os
import sys


def compute(list, n):
    sum = 0
    for i in range(n):
        sum += i
    return sum + len(str(os.sep)) + list[0]
