"""Planted regression: an OVERSIZE lane count.

The knob tuple that killed the r4 capture attempt: lane_T=131072 on the
plain reduced path, whose exact-seq XLA stats assembly failed remote
compile there (CLAUDE.md — the reason pick_lane_T filtered the rate
table at 65536 before graftmem derived the same cap).  The test asserts
memmodel.feasible rejects it NAMING the chain-stream buffers that
overflow the scoped-VMEM model.
"""

from cpgisland_tpu.analysis import memmodel

KERNEL = "assembly.seqstats.onehot"
KNOBS = memmodel.Knobs(lane_T=131072, lane_tile=256)
