"""jit-const-capture clean: big tables arrive as traced arguments, jnp
constructors are traced ops, and small host tables stay under budget."""

import numpy as np
import jax
import jax.numpy as jnp


@jax.jit
def score(obs, table):
    # The big table is a traced ARGUMENT — uploaded, never baked.
    return table[obs]


@jax.jit
def zeros_on_device(obs):
    # jnp constructors lower to ops, not constvars.
    acc = jnp.zeros((8192, 8192), jnp.float32)
    return acc.at[obs].add(1.0)


@jax.jit
def small_table(obs):
    # Small host constant: well under the remote-const budget.
    lut = np.arange(256)
    return jnp.asarray(lut)[obs]
