"""Planted regression: doubled sequential scan trip count.

Identical to ``cost_clean`` except the max-plus chain runs TWICE
(chained), doubling the serial-depth slope and the scan flops — the
static signature of an accidentally serialized second pass.  Must be
caught by the lockfile diff (serial_depth / flops drift, scan named).
"""

from cost_clean import BASE_SYMBOLS, _chain, _epilogue, _steps  # noqa: F401


def make(scale: int = 1):
    import jax.numpy as jnp
    import numpy as np

    obs = jnp.asarray(np.arange(BASE_SYMBOLS * scale, dtype=np.int32) % 4)

    def fn(o):
        steps = _steps(o)
        carry, ys = _chain(steps)
        carry2, ys2 = _chain(steps + carry[None, None, :])
        return carry2.sum() + ys.sum() + ys2.sum() + _epilogue()

    return fn, (obs,)
