"""retrace-hazard trigger: jitted callables taking raw Python scalars
without static_argnums/static_argnames."""

import jax


@jax.jit
def decode(obs, block_size: int = 4096):
    return obs.reshape(-1, block_size)


def windowed(obs, width: int):
    return obs[:width]


windowed_jit = jax.jit(windowed)
