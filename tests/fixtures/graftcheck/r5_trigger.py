"""no-stats-in-bwd-chain trigger: count tensors accumulated in a reverse
scan carry (the serialization the Pallas kernels must never reintroduce)."""

import jax
import jax.numpy as jnp


def backward_stats(A, emits, beta_T, zeros_kk):
    def bstep(carry, inp):
        beta_next, trans_acc = carry
        alpha_t, b_next = inp
        xi = alpha_t[:, None] * A * (b_next * beta_next)[None, :]
        trans_acc = trans_acc + xi  # stats sum rides the recurrence carry
        beta_t = jnp.matmul(A, b_next * beta_next)
        return (beta_t, trans_acc), None

    return jax.lax.scan(bstep, (beta_T, zeros_kk), emits, reverse=True)
