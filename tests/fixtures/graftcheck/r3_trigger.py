"""hot-path-host-sync trigger: unrouted blocking syncs in a marked hot path."""

import jax
import jax.numpy as jnp
import numpy as np


# graftcheck: hot-path
def decode_loop(params, spans):
    totals = []
    for s in spans:
        total_dev = jnp.dot(s, params)
        totals.append(np.asarray(total_dev))  # unrouted fetch
        score = float(jnp.max(total_dev))  # inline device scalar fetch
        jax.block_until_ready(total_dev)
        anchor = jax.device_get(total_dev)
        totals[-1].item()
    return totals, score, anchor
