"""sync-thread-lifecycle trigger: a non-daemon thread with no stop Event
and no join, whose target drains an iterator this file never closes (the
PR 5 prefetcher leak shape)."""

import threading


def _producer(it, sink) -> None:
    while True:
        try:
            sink.append(next(it))  # drains a generator forever
        except StopIteration:
            return


class Runner:
    def __init__(self) -> None:
        self._sink: list = []
        self._t = None

    def start(self, it) -> None:
        self._t = threading.Thread(target=_producer, args=(it, self._sink))
        self._t.start()
