"""sync-thread-lifecycle clean twin: daemonized thread with an owned stop
Event, a deterministic join, and a generator-close path on shutdown (the
prefetch._finish pattern)."""

import threading


def _close_iter(it) -> None:
    close = getattr(it, "close", None)
    if close is not None:
        close()


class Runner:
    def __init__(self) -> None:
        self._sink: list = []
        self._stop = threading.Event()
        self._it = None
        self._t = None

    def start(self, it) -> None:
        self._it = it
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._sink.append(next(self._it))
            except StopIteration:
                return

    def shutdown(self) -> None:
        self._stop.set()
        if self._t is not None:
            self._t.join()
        _close_iter(self._it)
