"""graftsync waiver fixture: one properly waived unguarded read (inline
form), one waiver missing its reason (does NOT waive), one stale waiver
covering nothing."""

import threading


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._n = 0

    def bump(self) -> None:
        with self._lock:
            self._n += 1

    def peek_waived(self) -> int:
        return self._n  # graftcheck: allow(sync-guarded-by) -- approximate display read: a torn int is impossible on CPython and the value is advisory

    def peek_unwaived(self) -> int:
        return self._n  # graftcheck: allow(sync-guarded-by)

    def stale(self) -> int:
        # graftcheck: allow(sync-lock-order) -- nothing here acquires two locks
        return 1
