"""sync-guarded-by trigger: attributes written under a lock, then read or
written elsewhere without it — the half-guarded-field lost-update shape."""

import threading

_stats_lock = threading.Lock()
_totals = {"n": 0}


def bump_total(k: int) -> None:
    with _stats_lock:
        _totals["n"] = _totals["n"] + k


def read_total() -> int:
    return _totals["n"]  # unguarded read of a module global written under lock


class Counter:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._count = 0
        self._events: list = []

    def bump(self) -> None:
        with self._lock:
            self._count += 1
            self._events.append("bump")

    def peek(self) -> int:
        return self._count  # unguarded read

    def reset(self) -> None:
        self._count = 0  # unguarded write
        self._events.clear()  # unguarded container mutation
