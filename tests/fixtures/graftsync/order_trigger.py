"""sync-lock-order trigger: two locks acquired in opposite orders (the
classic AB/BA static deadlock) plus a non-reentrant self-acquisition
through a helper call."""

import threading


class Pair:
    def __init__(self) -> None:
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self) -> None:
        with self._a:
            with self._b:
                pass

    def backward(self) -> None:
        with self._b:
            with self._a:  # inverted: B -> A while forward() takes A -> B
                pass


class Recurse:
    def __init__(self) -> None:
        self._mu = threading.Lock()

    def outer(self) -> None:
        with self._mu:
            self.inner()  # re-acquires the plain Lock it already holds

    def inner(self) -> None:
        with self._mu:
            pass
