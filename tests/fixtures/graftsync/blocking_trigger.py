"""sync-blocking-under-lock trigger: device fetches, queue ops, socket
I/O, sleeps, and a blocking helper call — all inside held critical
sections."""

import queue
import socket
import threading
import time

import jax


class Fetcher:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._last = None

    def fetch(self, x):
        with self._lock:
            self._last = jax.block_until_ready(x)  # device fetch under lock
            return self._last

    def push(self, item) -> None:
        with self._lock:
            self._q.put(item)  # blocking queue op under lock

    def read_wire(self) -> bytes:
        with self._lock:
            return self._sock.recv(4096)  # socket I/O under lock

    def nap(self) -> None:
        with self._lock:
            time.sleep(0.1)  # sleep under lock

    def indirect(self, x):
        with self._lock:
            return self._fetch_unlocked(x)  # helper that blocks, under lock

    def _fetch_unlocked(self, x):
        return jax.device_get(x)
