"""sync-guarded-by clean twin: every access to guarded state holds the
lock (or returns a copy taken under it)."""

import threading

_stats_lock = threading.Lock()
_totals = {"n": 0}


def bump_total(k: int) -> None:
    with _stats_lock:
        _totals["n"] = _totals["n"] + k


def read_total() -> int:
    with _stats_lock:
        return _totals["n"]


class Counter:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._count = 0
        self._events: list = []

    def bump(self) -> None:
        with self._lock:
            self._count += 1
            self._events.append("bump")

    def peek(self) -> int:
        with self._lock:
            return self._count

    def reset(self) -> None:
        with self._lock:
            self._count = 0
            self._events.clear()

    def _drain_locked(self) -> list:
        # The _locked-suffix convention: callers hold self._lock.
        out = list(self._events)
        self._events.clear()
        return out
