"""sync-blocking-under-lock clean twin: the prepared-cache discipline —
blocking work runs OUTSIDE the critical section, the lock only publishes
the result."""

import queue
import socket
import threading
import time

import jax


class Fetcher:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._last = None

    def fetch(self, x):
        got = jax.block_until_ready(x)  # fetch outside the lock...
        with self._lock:
            self._last = got  # ...publish under it
            return self._last

    def push(self, item) -> None:
        self._q.put(item)

    def read_wire(self) -> bytes:
        data = self._sock.recv(4096)
        with self._lock:
            self._last = data
        return data

    def nap(self) -> None:
        time.sleep(0.1)

    def indirect(self, x):
        got = self._fetch_unlocked(x)
        with self._lock:
            self._last = got
        return got

    def _fetch_unlocked(self, x):
        return jax.device_get(x)
