"""sync-lock-order clean twin: one global order (A before B) everywhere,
and the inner helper uses the _locked-suffix convention instead of
re-acquiring."""

import threading


class Pair:
    def __init__(self) -> None:
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self) -> None:
        with self._a:
            with self._b:
                pass

    def backward(self) -> None:
        with self._a:  # same order as forward(): A -> B
            with self._b:
                pass


class Recurse:
    def __init__(self) -> None:
        self._mu = threading.Lock()

    def outer(self) -> None:
        with self._mu:
            self._inner_locked()

    def _inner_locked(self) -> None:
        # Runs with self._mu held by the caller; takes nothing itself.
        pass
