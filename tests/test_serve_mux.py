"""Multi-connection serve mux (the ROADMAP response-muxing item), certified
under the graftsync runtime tracker: >= 4 concurrent AF_UNIX clients
streaming mixed decode+posterior requests through one daemon, every result
routed back to the owning connection, per-client results BIT-IDENTICAL to
the batch pipelines — with the tracker (a mini-TSan wrapping every lock the
serve stack creates, plus guarded-access descriptors on the broker's hot
counters) reporting ZERO lock-order or guarded-access violations.

Also pinned: per-connection drain-on-death (a dead client's requests still
complete and are dropped, never leaked into another client's stream) and
the daemon-wide request-id space (a colliding id from a second connection
is rejected while the first is in flight).
"""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from cpgisland_tpu import pipeline, resilience
from cpgisland_tpu.analysis import tracksync
from cpgisland_tpu.models import presets
from cpgisland_tpu.serve import BrokerConfig, RequestBroker, Session
from cpgisland_tpu.serve.transport import serve_socket

BASES = np.array(list("acgt"))


@pytest.fixture(autouse=True)
def _fresh_resilience_state():
    resilience.reset()
    yield
    resilience.reset()


@pytest.fixture()
def tracker():
    # ensure_installed composes with CPGISLAND_TRACKSYNC=1: the stress
    # runs under the session-wide tracker when one is active (uninstall is
    # a no-op there), else installs its own for the test's duration.
    tr, uninstall = tracksync.ensure_installed()
    try:
        yield tr
    finally:
        uninstall()


def _gen_symbols(rng, n: int) -> np.ndarray:
    bg = rng.choice(4, size=n, p=[0.3, 0.2, 0.2, 0.3])
    k = max(1, n // 4)
    bg[:k] = rng.choice(4, size=k, p=[0.1, 0.4, 0.4, 0.1])
    return bg.astype(np.uint8)


def _seq_text(syms: np.ndarray) -> str:
    return "".join(BASES[syms])


def _write_fasta(path, records) -> str:
    with open(path, "w") as f:
        for name, syms in records:
            f.write(f">{name}\n")
            s = _seq_text(syms)
            for i in range(0, len(s), 70):
                f.write(s[i : i + 70] + "\n")
    return str(path)


def _islands_by_name(calls) -> dict:
    """name -> reference-format text (the bit-exact comparison unit the
    serve protocol ships as ``islands_text``; the batch pipelines emit one
    name-prefixed stream, split here per record)."""
    out: dict = {}
    for line in calls.format_lines().splitlines(keepends=True):
        out.setdefault(line.split(" ", 1)[0], []).append(line)
    return {name: "".join(lines) for name, lines in out.items()}


def _start_server(broker, sock_path, **kw):
    t = threading.Thread(
        target=serve_socket, args=(sock_path, broker), kwargs=kw,
        name="mux-server", daemon=True,
    )
    t.start()
    deadline = time.monotonic() + 30.0
    while not os.path.exists(sock_path):
        assert time.monotonic() < deadline, "server socket never appeared"
        time.sleep(0.01)
    # Bindable != acceptable: retry the first connect briefly.
    while True:
        try:
            _probe_connect(sock_path).close()
            break
        except OSError:
            assert time.monotonic() < deadline
            time.sleep(0.05)
    return t


def _probe_connect(sock_path):
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.connect(sock_path)
    return s


def _client_session(sock_path, requests):
    """Open one connection, submit every request, read until every id has
    a response line; returns {id: wire dict}."""
    s = _probe_connect(sock_path)
    rf = s.makefile("r", encoding="utf-8")
    wf = s.makefile("w", encoding="utf-8")
    want = set()
    for req in requests:
        wf.write(json.dumps(req) + "\n")
        want.add(req["id"])
    wf.flush()
    got: dict = {}
    for line in rf:
        obj = json.loads(line)
        if obj.get("id") in want:
            got[obj["id"]] = obj
        if set(got) == want:
            break
    rf.close()
    wf.close()
    s.close()
    return got


def _send_shutdown(sock_path):
    s = _probe_connect(sock_path)
    s.sendall(b'{"op": "shutdown"}\n')
    s.close()


N_CLIENTS = 4


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_mux_concurrent_clients_bit_identical_under_tracker(
    tmp_path, tracker
):
    params = presets.durbin_cpg8()
    rng = np.random.default_rng(11)
    lengths = [400, 900, 1500, 2200]

    # Per-client request sets: disjoint id ranges (the daemon-wide id
    # space), mixed decode+posterior, two tenants.
    clients: list = []
    all_decode: list = []
    all_post: list = []
    for c in range(N_CLIENTS):
        reqs = []
        for k in range(4):
            name = f"c{c}r{k}"
            syms = _gen_symbols(rng, lengths[k] + 17 * c)
            kind = "decode" if (c + k) % 2 == 0 else "posterior"
            (all_decode if kind == "decode" else all_post).append(
                (name, syms)
            )
            reqs.append({
                "id": c * 1000 + k, "kind": kind, "seq": _seq_text(syms),
                "tenant": f"t{c % 2}", "name": name,
                "want_conf": kind == "posterior",
            })
        clients.append(reqs)

    # Batch-pipeline ground truth on the same records (outside the serve
    # stack; the tracker only needs to cover the daemon's locks).
    dres = pipeline.decode_file(
        _write_fasta(tmp_path / "d.fa", all_decode), params, compat=False
    )
    conf_path = str(tmp_path / "conf.npy")
    pres = pipeline.posterior_file(
        _write_fasta(tmp_path / "p.fa", all_post), params,
        confidence_out=conf_path,
        islands_out=str(tmp_path / "pi.txt"),
    )
    want_decode = _islands_by_name(dres.calls)
    want_post = _islands_by_name(pres.calls)
    conf_all = np.load(conf_path)
    want_conf: dict = {}
    off = 0
    for nm, syms in all_post:
        want_conf[nm] = conf_all[off : off + syms.size]
        off += syms.size

    # The serve stack, built INSIDE the tracker window: every lock the
    # session/broker/mux create is wrapped and recorded.
    sess = Session(params, name="mux-test", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=6_000, flush_deadline_s=0.05)
    )
    # Guarded-access descriptors on the broker's shared counters: any
    # unlocked read/write from any thread is a recorded violation.
    tracker.watch_attrs(
        broker, broker._lock,
        ["_queued_symbols", "flushes", "flushed_symbols"],
        label="RequestBroker",
    )
    sock_path = str(tmp_path / "mux.sock")
    server = _start_server(broker, sock_path)

    results: list = [None] * N_CLIENTS
    errors: list = []

    def run_client(c):
        try:
            results[c] = _client_session(sock_path, clients[c])
        except Exception as e:  # surface in the main thread's assert
            errors.append((c, repr(e)))

    threads = [
        threading.Thread(target=run_client, args=(c,), name=f"client{c}")
        for c in range(N_CLIENTS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300.0)
    assert errors == [], errors
    assert all(r is not None for r in results)

    _send_shutdown(sock_path)
    server.join(timeout=60.0)
    assert not server.is_alive()

    # Every client got exactly its own ids, bit-identical to the batch
    # pipelines: reference-format island text, and per-symbol conf.
    for c in range(N_CLIENTS):
        got = results[c]
        assert set(got) == {r["id"] for r in clients[c]}
        for req in clients[c]:
            r = got[req["id"]]
            assert r["ok"], r.get("error")
            assert r["tenant"] == req["tenant"]
            name = req["name"]
            want = (
                want_decode if req["kind"] == "decode" else want_post
            ).get(name, "")
            assert r.get("islands_text", "") == want, name
            if req["kind"] == "posterior":
                got_conf = np.asarray(r["conf"], np.float32)
                assert np.array_equal(got_conf, want_conf[name]), name

    # The certification this test exists for: a real concurrent load with
    # ZERO lock-order or guarded-access violations observed.
    tracker.assert_clean()
    s = tracker.summary()
    assert s["acquires"] > 100  # the load actually exercised the locks
    assert s["guarded_checks"] > 10  # the descriptors actually checked
    # And the daemon really muxed: both tenants served over one broker.
    stats = broker.stats()
    assert set(stats["tenants"]) == {"t0", "t1"}
    assert stats["flushes"] >= 2


def test_mux_dead_client_drains_without_leaking(tmp_path, tracker):
    params = presets.durbin_cpg8()
    rng = np.random.default_rng(3)
    sess = Session(params, name="mux-dead", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 20, flush_deadline_s=0.2)
    )
    sock_path = str(tmp_path / "dead.sock")
    server = _start_server(broker, sock_path)

    # Client A submits and disconnects WITHOUT reading its result.
    sa = _probe_connect(sock_path)
    req_a = {"id": 1, "kind": "decode",
             "seq": _seq_text(_gen_symbols(rng, 600)), "name": "a"}
    sa.sendall((json.dumps(req_a) + "\n").encode())
    sa.close()

    # Client B's stream must receive ONLY its own result; A's completes
    # and is dropped by the router (drain-on-death), not re-routed.
    syms_b = _gen_symbols(rng, 600)
    got = _client_session(
        sock_path,
        [{"id": 2, "kind": "decode", "seq": _seq_text(syms_b),
          "name": "b"}],
    )
    assert set(got) == {2} and got[2]["ok"]

    _send_shutdown(sock_path)
    server.join(timeout=60.0)
    # A's request was still flushed (the shared queue stayed clean).
    assert broker.stats()["flushed_symbols"] >= 1200
    tracker.assert_clean()


def test_mux_stalled_client_does_not_wedge_other_clients(tmp_path, tracker):
    """A client that stops READING must not stall the worker's result
    delivery for everyone: once its send buffer fills, the bounded write
    (``write_timeout_s``) marks it dead and later results are dropped —
    the healthy client still receives everything."""
    params = presets.durbin_cpg8()
    rng = np.random.default_rng(9)
    sess = Session(params, name="mux-stall", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=6_000, flush_deadline_s=0.05)
    )
    sock_path = str(tmp_path / "stall.sock")
    server = _start_server(broker, sock_path, write_timeout_s=1.0)

    # The staller: want_conf posterior results are ~50 KB of JSON each;
    # ten of them overflow any default AF_UNIX send buffer.  Keep the
    # socket OPEN and never read it.
    stall = _probe_connect(sock_path)
    for k in range(10):
        syms = _gen_symbols(rng, 3000)
        stall.sendall((json.dumps({
            "id": 100 + k, "kind": "posterior", "seq": _seq_text(syms),
            "name": f"s{k}", "want_conf": True,
        }) + "\n").encode())

    # The healthy client, concurrently: must receive all of its results
    # even while the staller's buffer is wedged.
    reqs = [
        {"id": 7 + k, "kind": "decode",
         "seq": _seq_text(_gen_symbols(rng, 800)), "name": f"h{k}"}
        for k in range(3)
    ]
    got: dict = {}
    done = threading.Event()

    def healthy():
        got.update(_client_session(sock_path, reqs))
        done.set()

    t = threading.Thread(target=healthy, daemon=True)
    t.start()
    assert done.wait(timeout=120.0), (
        "healthy client starved behind the stalled connection"
    )
    assert set(got) == {7, 8, 9} and all(r["ok"] for r in got.values())

    _send_shutdown(sock_path)
    server.join(timeout=60.0)
    assert not server.is_alive()
    stall.close()
    tracker.assert_clean()


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_mux_fleet_two_devices_one_quarantined_mid_stream(tmp_path, tracker):
    """PR 15 fleet under the mux + runtime tracker: a 2-device DevicePool
    serves concurrent clients; ONE device is quarantined mid-stream
    (health signal, no probe during the test) and the remaining traffic
    fails over to the healthy device — per-client results stay
    BIT-IDENTICAL to the batch pipelines and the tracker observes zero
    lock-order or guarded-access violations across the pool/health/
    journal locks."""
    from cpgisland_tpu.serve import DevicePool, FleetConfig

    params = presets.durbin_cpg8()
    rng = np.random.default_rng(31)
    lengths = [450, 1000, 1600, 2100]
    clients: list = []
    all_decode: list = []
    all_post: list = []
    for c in range(N_CLIENTS):
        reqs = []
        for k in range(4):
            name = f"f{c}r{k}"
            syms = _gen_symbols(rng, lengths[k] + 13 * c)
            kind = "decode" if (c + k) % 2 == 0 else "posterior"
            (all_decode if kind == "decode" else all_post).append(
                (name, syms)
            )
            reqs.append({
                "id": c * 1000 + k, "kind": kind, "seq": _seq_text(syms),
                "tenant": f"t{c % 2}", "name": name,
            })
        clients.append(reqs)

    dres = pipeline.decode_file(
        _write_fasta(tmp_path / "fd.fa", all_decode), params, compat=False
    )
    pres = pipeline.posterior_file(
        _write_fasta(tmp_path / "fp.fa", all_post), params,
        islands_out=str(tmp_path / "fpi.txt"),
    )
    want_decode = _islands_by_name(dres.calls)
    want_post = _islands_by_name(pres.calls)

    # Built INSIDE the tracker window: pool + health + journal locks are
    # all wrapped and recorded.
    sess = Session(params, name="mux-fleet", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=6_000, flush_deadline_s=0.05)
    )
    # Huge cooldown: the quarantined device stays OUT for the whole test
    # (no half-open probe muddying the "one quarantined" invariant).
    pool = DevicePool.build(
        broker, n_devices=2, config=FleetConfig(cooldown_s=1e9)
    )
    tracker.watch_attrs(
        broker, broker._lock,
        ["_queued_symbols", "flushes", "flushed_symbols"],
        label="RequestBroker",
    )
    tracker.watch_attrs(
        pool, pool._lock, ["requeues", "failed_over"], label="DevicePool",
    )
    sock_path = str(tmp_path / "fleet.sock")
    server = _start_server(broker, sock_path, pool=pool)

    # Round A: first half of each client's stream on both devices.
    results_a: list = [None] * N_CLIENTS
    results_b: list = [None] * N_CLIENTS
    errors: list = []

    def client_round(c, reqs, out):
        try:
            out[c] = _client_session(sock_path, reqs)
        except Exception as e:
            errors.append((c, repr(e)))

    threads = [
        threading.Thread(target=client_round,
                         args=(c, clients[c][:2], results_a))
        for c in range(N_CLIENTS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300.0)
    assert errors == [], errors

    # Mid-stream: pull dev0 out of rotation (the health-signal path the
    # supervisor monitor drives; graftfault covers the injected-fault
    # route deterministically in test_graftfault.py).
    pool.workers[0].health.force_quarantine("mid-stream")

    # Round B: the rest of the stream — served entirely by dev1.
    threads = [
        threading.Thread(target=client_round,
                         args=(c, clients[c][2:], results_b))
        for c in range(N_CLIENTS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300.0)
    assert errors == [], errors

    _send_shutdown(sock_path)
    server.join(timeout=60.0)
    assert not server.is_alive()
    pool.close()

    for c in range(N_CLIENTS):
        got = dict(results_a[c] or {})
        got.update(results_b[c] or {})
        assert set(got) == {r["id"] for r in clients[c]}
        for req in clients[c]:
            r = got[req["id"]]
            assert r["ok"], r.get("error")
            name = req["name"]
            want = (
                want_decode if req["kind"] == "decode" else want_post
            ).get(name, "")
            assert r.get("islands_text", "") == want, name

    tracker.assert_clean()
    st = pool.stats()
    assert st["devices"]["dev0"]["state"] == "quarantined"
    assert st["devices"]["dev0"]["quarantines"] == 1
    # The fleet really served: every round-B flush ran on dev1.
    assert st["devices"]["dev1"]["flushes"] >= 1
    assert broker.stats()["flushes"] >= 2


def test_mux_duplicate_id_across_connections_rejected(tmp_path, tracker):
    params = presets.durbin_cpg8()
    rng = np.random.default_rng(5)
    sess = Session(params, name="mux-dup", private_breaker=True)
    # Big budget + long deadline: A's request stays QUEUED while B collides.
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 20, flush_deadline_s=1.0)
    )
    sock_path = str(tmp_path / "dup.sock")
    server = _start_server(broker, sock_path)

    sa = _probe_connect(sock_path)
    rfa = sa.makefile("r", encoding="utf-8")
    seq = _seq_text(_gen_symbols(rng, 500))
    sa.sendall((json.dumps(
        {"id": 5, "kind": "decode", "seq": seq, "name": "a"}
    ) + "\n").encode())

    # B reuses id 5 while A's is in flight: rejected at the router with
    # the id named, and A's route is untouched.
    sb = _probe_connect(sock_path)
    rfb = sb.makefile("r", encoding="utf-8")
    sb.sendall((json.dumps(
        {"id": 5, "kind": "decode", "seq": seq, "name": "b"}
    ) + "\n").encode())
    rej = json.loads(rfb.readline())
    assert rej["ok"] is False and "already in flight" in rej["error"]
    rfb.close()
    sb.close()

    # A still receives ITS result (the deadline flush).
    ra = json.loads(rfa.readline())
    assert ra["id"] == 5 and ra["ok"]
    rfa.close()
    sa.close()

    _send_shutdown(sock_path)
    server.join(timeout=60.0)
    tracker.assert_clean()
