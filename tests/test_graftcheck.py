"""Per-rule graftcheck unit tests: one triggering and one clean fixture per
rule, waiver parsing, hot-path registration, the CLI exit-code contract,
and the Layer-3 cost-lockfile CLI workflow (tolerance boundaries, the
--update-costs round trip, stale-entry reporting).

The lint-layer tests touch no jax; the cost-CLI tests run the tracing in
subprocesses (tests/test_graftcheck_self.py covers the in-process jaxpr
contract and cost layers).
"""

import json
import os
import subprocess
import sys

import pytest

from cpgisland_tpu.analysis import all_rules, cost_contracts, lint_file
from cpgisland_tpu.analysis.config import hot_functions_for
from cpgisland_tpu.analysis.core import parse_waivers

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "graftcheck")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RULES = [
    ("jit-big-closure", "r1"),
    ("pallas-sublane-align", "r2"),
    ("hot-path-host-sync", "r3"),
    ("maxplus-normalize", os.path.join("parallel", "r4")),
    ("no-stats-in-bwd-chain", "r5"),
    ("retrace-hazard", "r6"),
    ("jit-const-capture", "r7"),
    ("trace-time-consult", "r8"),
]


def _lint(name: str):
    path = os.path.join(FIXTURES, f"{name}.py")
    # relpath keeps the fixture's directory shape (the R4 rule scopes on
    # parallel/ in the path).
    findings, waivers = lint_file(path, relpath=os.path.relpath(path, REPO))
    return findings, waivers


@pytest.mark.parametrize("rule,stem", RULES, ids=[r for r, _ in RULES])
def test_rule_fires_on_trigger(rule, stem):
    findings, _ = _lint(f"{stem}_trigger")
    hits = [f for f in findings if f.rule == rule and not f.waived]
    assert hits, f"{rule} did not fire on its trigger fixture"


@pytest.mark.parametrize("rule,stem", RULES, ids=[r for r, _ in RULES])
def test_rule_quiet_on_clean(rule, stem):
    findings, _ = _lint(f"{stem}_clean")
    hits = [f for f in findings if f.rule == rule]
    assert hits == [], [f.format() for f in hits]


def test_r2_flags_each_mosaic_antipattern():
    findings, _ = _lint("r2_trigger")
    msgs = "\n".join(
        f.message for f in findings if f.rule == "pallas-sublane-align"
    )
    assert "not provably 8-aligned" in msgs
    assert "rank-3" in msgs
    assert "_bcast_tab" in msgs


def test_r3_flags_every_banned_spelling():
    findings, _ = _lint("r3_trigger")
    msgs = "\n".join(
        f.message for f in findings if f.rule == "hot-path-host-sync"
    )
    for spelling in (".item()", "float()", "asarray", "block_until_ready",
                     "device_get"):
        assert spelling in msgs, f"missing {spelling} in:\n{msgs}"


def test_r6_flags_both_wrapper_forms():
    findings, _ = _lint("r6_trigger")
    hits = [f for f in findings if f.rule == "retrace-hazard"]
    assert len(hits) >= 2  # decorator form + jax.jit(fn) call form
    assert any("block_size" in f.message for f in hits)
    assert any("width" in f.message for f in hits)


def test_hygiene_rules():
    findings, _ = _lint("hygiene_trigger")
    rules = {f.rule for f in findings}
    assert "unused-import" in rules
    assert "shadow-builtin" in rules


# -- waivers -----------------------------------------------------------------


def test_waiver_inline_and_standalone_forms():
    findings, waivers = _lint("waivers")
    r1 = [f for f in findings if f.rule == "jit-big-closure"]
    waived = [f for f in r1 if f.waived]
    unwaived = [f for f in r1 if not f.waived]
    assert len(waived) == 2  # inline + standalone-comment forms
    assert all(f.waiver_reason for f in waived)
    assert len(unwaived) == 1  # the missing-reason waiver does NOT waive
    assert any(f.rule == "waiver-syntax" for f in findings)
    stale = [w for w in waivers if not w.used]
    assert any("maxplus-normalize" in w.rules for w in stale)


def test_waiver_only_covers_named_rule():
    findings, _ = lint_file(
        os.path.join(FIXTURES, "waivers.py"),
        relpath="tests/fixtures/graftcheck/waivers.py",
    )
    # A jit-big-closure waiver must not suppress other rules on the line.
    for f in findings:
        if f.waived:
            assert f.rule == "jit-big-closure"


def test_waiver_regex_requires_reason():
    waivers, errors = parse_waivers(
        "x = 1  # graftcheck: allow(some-rule)\n"
        "y = 2  # graftcheck: allow(other-rule) -- because measured\n"
    )
    assert len(waivers) == 1 and waivers[0].rules == ("other-rule",)
    assert len(errors) == 1 and "justification" in errors[0][1]


def test_waivers_in_docstrings_are_inert():
    waivers, errors = parse_waivers(
        '"""docs: # graftcheck: allow(some-rule) -- example"""\nx = 1\n'
    )
    assert waivers == [] and errors == []


# -- registration ------------------------------------------------------------


def test_hot_path_registry_matches_repo_layout():
    assert "viterbi_sharded_spans" in hot_functions_for(
        "cpgisland_tpu/parallel/decode.py"
    )
    assert "_fit_fused" in hot_functions_for("cpgisland_tpu/train/baum_welch.py")
    assert hot_functions_for("cpgisland_tpu/models/hmm.py") == frozenset()


def test_all_six_issue_rules_registered():
    names = set(all_rules())
    for rule, _ in RULES:
        assert rule in names
    assert {"unused-import", "shadow-builtin"} <= names


# -- CLI ---------------------------------------------------------------------


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "cpgisland_tpu.analysis", *args],
        capture_output=True, text=True, cwd=REPO,
    )


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_cli_exits_nonzero_on_each_trigger():
    for _, stem in RULES:
        proc = _run_cli(os.path.join(FIXTURES, f"{stem}_trigger.py"))
        assert proc.returncode == 1, (stem, proc.stdout, proc.stderr)


def test_cli_exits_zero_on_clean_fixture():
    proc = _run_cli(os.path.join(FIXTURES, "r6_clean.py"))
    assert proc.returncode == 0, (proc.stdout, proc.stderr)


def test_cli_list_rules_and_json():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    assert "jit-big-closure" in proc.stdout and "origin:" in proc.stdout
    # Layer 3: the quantitative cost contracts are part of the catalogue.
    assert "cost.lockfile" in proc.stdout
    assert "cost.reduced-no-dense-pair" in proc.stdout
    assert "cost.em-body-fixed-share" in proc.stdout

    proc = _run_cli("--json", os.path.join(FIXTURES, "r1_trigger.py"))
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["ok"] is False
    assert any(f["rule"] == "jit-big-closure" for f in payload["findings"])


def test_cli_unknown_rule_is_usage_error():
    proc = _run_cli("--rules", "no-such-rule",
                    os.path.join(FIXTURES, "r1_clean.py"))
    assert proc.returncode == 2


# -- suite infra: the on-TPU skip-reason gate (VERDICT r5 #4) ----------------


def test_tpu_skip_allowlist_covers_every_known_reason_class():
    """Every skip reason the suite can emit matches the conftest registry,
    and an arbitrary reason does NOT — so on TPU an unexplained skip fails
    instead of hiding in a green artifact."""
    from conftest import _TPU_SKIP_ALLOWED

    known = [
        "needs 8 devices, have 1",
        "off-TPU expectation test",
        "compile-diversity fuzz is CPU-suite coverage",
        "device-count contract applies to the virtual CPU mesh",
        "jax 0.4.37 CPU backend lacks multi-process collectives",
        "native library unavailable (no C++ toolchain?)",
        "host-callback probe failed: RuntimeError: x",
        "no driver BENCH_r*.json present",
        "capture r06 is newer than the driver record r05",
    ]
    for reason in known:
        assert any(p.search(reason) for p in _TPU_SKIP_ALLOWED), reason
    for bogus in ("TPU path quietly disabled", "skipping for now", ""):
        assert not any(p.search(bogus) for p in _TPU_SKIP_ALLOWED), bogus


# -- Layer 3: tolerance boundaries (pure dict math, no tracing) --------------


def _fp(flops_ps=100.0, flops_fixed=10.0, prims=None, prim_flops=None,
        passes=1, n_eqns=5, depth_ps=0.01, depth_fixed=50.0):
    m = {
        "flops": 1000, "bytes": 2000, "serial_depth": 50, "n_eqns": n_eqns,
        "prims": dict(prims or {"add": 3, "scan": 1}),
        "prim_flops": dict(prim_flops or {"add": 900.0}),
        "n_scan_eqns": 1,
    }
    return {
        "geometries": [100, 200], "passes": passes, "metrics": [m, m],
        "fits": {
            "flops": {"per_symbol": flops_ps, "fixed": flops_fixed},
            "bytes": {"per_symbol": 20.0, "fixed": 100.0},
            "serial_depth": {"per_symbol": depth_ps, "fixed": depth_fixed},
        },
    }


def _lock_for(fp, tolerances=None):
    lock = {
        "version": 1,
        "tolerances": dict(tolerances or {}),
        "platforms": {"cpu": {"jax": "x", "entries": {"e": fp}}},
    }
    return lock


def test_cost_diff_inside_tolerance_passes():
    lock = _lock_for(_fp(flops_ps=100.0))
    live = {"e": _fp(flops_ps=101.9)}  # +1.9% < 2% tolerance
    diff = cost_contracts.diff_costs(live, lock, "cpu")
    assert diff.ok, diff.violations


def test_cost_diff_past_tolerance_fails_naming_prims():
    lock = _lock_for(_fp(flops_ps=100.0, prim_flops={"add": 900.0}))
    live = {"e": _fp(flops_ps=102.1, prim_flops={"add": 950.0})}  # +2.1%
    diff = cost_contracts.diff_costs(live, lock, "cpu")
    assert not diff.ok
    assert any("flops.per_symbol" in v and "add" in v
               for v in diff.violations), diff.violations


def test_cost_diff_tolerance_overridable_from_lockfile():
    lock = _lock_for(_fp(flops_ps=100.0), tolerances={"flops": 0.10})
    live = {"e": _fp(flops_ps=105.0)}  # +5% < the widened 10%
    diff = cost_contracts.diff_costs(live, lock, "cpu")
    assert diff.ok, diff.violations


def test_cost_diff_pass_count_is_exact():
    lock = _lock_for(_fp(passes=1))
    live = {"e": _fp(passes=2)}
    diff = cost_contracts.diff_costs(live, lock, "cpu")
    assert not diff.ok
    assert any("pass count" in v for v in diff.violations)


def test_cost_diff_eqn_count_is_exact():
    lock = _lock_for(_fp(n_eqns=5))
    live = {"e": _fp(n_eqns=6, prims={"add": 4, "scan": 1})}
    diff = cost_contracts.diff_costs(live, lock, "cpu")
    assert not diff.ok
    assert any("eqn count" in v and "add+1" in v for v in diff.violations)


# -- Layer 3: the --update-costs CLI round trip ------------------------------


@pytest.mark.slow
def test_cli_update_costs_round_trip(tmp_path):
    lockfile = str(tmp_path / "COSTS.json")
    # 1. Baseline: --update-costs writes the lockfile and exits 0.
    proc = _run_cli("--no-lint", "--update-costs", "--costs-file", lockfile)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "re-baselined" in proc.stderr
    assert os.path.exists(lockfile)
    with open(lockfile) as fh:
        data = json.load(fh)
    entries = data["platforms"]["cpu"]["entries"]
    assert "em.seq.onehot" in entries and "em.fused" in entries

    # 2. Corrupt one fitted value past tolerance: --costs fails, naming
    #    the entry and the metric.
    entries["em.seq.onehot"]["fits"]["flops"]["per_symbol"] *= 1.5
    with open(lockfile, "w") as fh:
        json.dump(data, fh)
    proc = _run_cli("--no-lint", "--costs", "--costs-file", lockfile)
    assert proc.returncode == 1, (proc.stdout, proc.stderr)
    assert "em.seq.onehot" in proc.stdout and "flops.per_symbol" in proc.stdout

    # 3. A stale entry (removed from the registry) is reported like a
    #    stale waiver — a note, not a failure.
    entries["em.seq.onehot"]["fits"]["flops"]["per_symbol"] /= 1.5
    entries["em.ghost"] = entries["em.mstep"]
    with open(lockfile, "w") as fh:
        json.dump(data, fh)
    proc = _run_cli("--no-lint", "--costs", "--costs-file", lockfile)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "stale lockfile entry 'em.ghost'" in proc.stderr

    # 4. --update-costs re-baselines: stale entry dropped, summary printed,
    #    and a fresh --costs run is green.
    proc = _run_cli("--no-lint", "--update-costs", "--costs-file", lockfile)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "- em.ghost (stale entry removed)" in proc.stderr
    with open(lockfile) as fh:
        data = json.load(fh)
    assert "em.ghost" not in data["platforms"]["cpu"]["entries"]
    proc = _run_cli("--no-lint", "--costs", "--costs-file", lockfile)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
