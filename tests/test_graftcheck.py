"""Per-rule graftcheck unit tests: one triggering and one clean fixture per
rule, waiver parsing, hot-path registration, and the CLI exit-code contract.

Pure-AST layer — nothing here touches jax, so the whole file runs in well
under a second (tests/test_graftcheck_self.py covers the jaxpr contracts).
"""

import os
import subprocess
import sys

import pytest

from cpgisland_tpu.analysis import all_rules, lint_file
from cpgisland_tpu.analysis.config import hot_functions_for
from cpgisland_tpu.analysis.core import parse_waivers

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "graftcheck")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RULES = [
    ("jit-big-closure", "r1"),
    ("pallas-sublane-align", "r2"),
    ("hot-path-host-sync", "r3"),
    ("maxplus-normalize", os.path.join("parallel", "r4")),
    ("no-stats-in-bwd-chain", "r5"),
    ("retrace-hazard", "r6"),
]


def _lint(name: str):
    path = os.path.join(FIXTURES, f"{name}.py")
    # relpath keeps the fixture's directory shape (the R4 rule scopes on
    # parallel/ in the path).
    findings, waivers = lint_file(path, relpath=os.path.relpath(path, REPO))
    return findings, waivers


@pytest.mark.parametrize("rule,stem", RULES, ids=[r for r, _ in RULES])
def test_rule_fires_on_trigger(rule, stem):
    findings, _ = _lint(f"{stem}_trigger")
    hits = [f for f in findings if f.rule == rule and not f.waived]
    assert hits, f"{rule} did not fire on its trigger fixture"


@pytest.mark.parametrize("rule,stem", RULES, ids=[r for r, _ in RULES])
def test_rule_quiet_on_clean(rule, stem):
    findings, _ = _lint(f"{stem}_clean")
    hits = [f for f in findings if f.rule == rule]
    assert hits == [], [f.format() for f in hits]


def test_r2_flags_each_mosaic_antipattern():
    findings, _ = _lint("r2_trigger")
    msgs = "\n".join(
        f.message for f in findings if f.rule == "pallas-sublane-align"
    )
    assert "not provably 8-aligned" in msgs
    assert "rank-3" in msgs
    assert "_bcast_tab" in msgs


def test_r3_flags_every_banned_spelling():
    findings, _ = _lint("r3_trigger")
    msgs = "\n".join(
        f.message for f in findings if f.rule == "hot-path-host-sync"
    )
    for spelling in (".item()", "float()", "asarray", "block_until_ready",
                     "device_get"):
        assert spelling in msgs, f"missing {spelling} in:\n{msgs}"


def test_r6_flags_both_wrapper_forms():
    findings, _ = _lint("r6_trigger")
    hits = [f for f in findings if f.rule == "retrace-hazard"]
    assert len(hits) >= 2  # decorator form + jax.jit(fn) call form
    assert any("block_size" in f.message for f in hits)
    assert any("width" in f.message for f in hits)


def test_hygiene_rules():
    findings, _ = _lint("hygiene_trigger")
    rules = {f.rule for f in findings}
    assert "unused-import" in rules
    assert "shadow-builtin" in rules


# -- waivers -----------------------------------------------------------------


def test_waiver_inline_and_standalone_forms():
    findings, waivers = _lint("waivers")
    r1 = [f for f in findings if f.rule == "jit-big-closure"]
    waived = [f for f in r1 if f.waived]
    unwaived = [f for f in r1 if not f.waived]
    assert len(waived) == 2  # inline + standalone-comment forms
    assert all(f.waiver_reason for f in waived)
    assert len(unwaived) == 1  # the missing-reason waiver does NOT waive
    assert any(f.rule == "waiver-syntax" for f in findings)
    stale = [w for w in waivers if not w.used]
    assert any("maxplus-normalize" in w.rules for w in stale)


def test_waiver_only_covers_named_rule():
    findings, _ = lint_file(
        os.path.join(FIXTURES, "waivers.py"),
        relpath="tests/fixtures/graftcheck/waivers.py",
    )
    # A jit-big-closure waiver must not suppress other rules on the line.
    for f in findings:
        if f.waived:
            assert f.rule == "jit-big-closure"


def test_waiver_regex_requires_reason():
    waivers, errors = parse_waivers(
        "x = 1  # graftcheck: allow(some-rule)\n"
        "y = 2  # graftcheck: allow(other-rule) -- because measured\n"
    )
    assert len(waivers) == 1 and waivers[0].rules == ("other-rule",)
    assert len(errors) == 1 and "justification" in errors[0][1]


def test_waivers_in_docstrings_are_inert():
    waivers, errors = parse_waivers(
        '"""docs: # graftcheck: allow(some-rule) -- example"""\nx = 1\n'
    )
    assert waivers == [] and errors == []


# -- registration ------------------------------------------------------------


def test_hot_path_registry_matches_repo_layout():
    assert "viterbi_sharded_spans" in hot_functions_for(
        "cpgisland_tpu/parallel/decode.py"
    )
    assert "_fit_fused" in hot_functions_for("cpgisland_tpu/train/baum_welch.py")
    assert hot_functions_for("cpgisland_tpu/models/hmm.py") == frozenset()


def test_all_six_issue_rules_registered():
    names = set(all_rules())
    for rule, _ in RULES:
        assert rule in names
    assert {"unused-import", "shadow-builtin"} <= names


# -- CLI ---------------------------------------------------------------------


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "cpgisland_tpu.analysis", *args],
        capture_output=True, text=True, cwd=REPO,
    )


def test_cli_exits_nonzero_on_each_trigger():
    for _, stem in RULES:
        proc = _run_cli(os.path.join(FIXTURES, f"{stem}_trigger.py"))
        assert proc.returncode == 1, (stem, proc.stdout, proc.stderr)


def test_cli_exits_zero_on_clean_fixture():
    proc = _run_cli(os.path.join(FIXTURES, "r6_clean.py"))
    assert proc.returncode == 0, (proc.stdout, proc.stderr)


def test_cli_list_rules_and_json():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    assert "jit-big-closure" in proc.stdout and "origin:" in proc.stdout

    import json

    proc = _run_cli("--json", os.path.join(FIXTURES, "r1_trigger.py"))
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["ok"] is False
    assert any(f["rule"] == "jit-big-closure" for f in payload["findings"])


def test_cli_unknown_rule_is_usage_error():
    proc = _run_cli("--rules", "no-such-rule",
                    os.path.join(FIXTURES, "r1_clean.py"))
    assert proc.returncode == 2
