"""Streaming/prefetch layer: parity with the serial path + thread hygiene.

The overlapped pipeline (pipeline.decode_file / posterior_file with
``prefetch > 0``) must change ONLY dispatch/fetch timing: island calls are
bit-identical to the serial cadence, no prefetch thread outlives its
pipeline call (the module-scoped clear_caches fixture must never see a
stale producer), and with telemetry off the overlap adds zero device
dispatches of its own.
"""

import threading
import time

import numpy as np
import pytest

from cpgisland_tpu import obs, pipeline
from cpgisland_tpu.models import presets
from cpgisland_tpu.utils.prefetch import RecordPrefetcher, maybe_prefetch


def _prefetch_threads() -> list:
    return [
        t for t in threading.enumerate()
        if t.name.startswith("cpgisland-prefetch")
    ]


def _write_fasta(path, rng, n_records=7, scale=1):
    """Multi-record FASTA with planted CG-rich islands; record sizes spread
    so both the batched small-record path and per-record decode run."""
    bases = np.array(list("acgt"))
    with open(path, "w") as f:
        for r in range(n_records):
            f.write(f">rec{r}\n")
            n = (512 + 768 * r) * scale
            bg = rng.choice(4, size=n, p=[0.3, 0.2, 0.2, 0.3])
            bg[: n // 4] = rng.choice(4, size=n // 4, p=[0.1, 0.4, 0.4, 0.1])
            s = "".join(bases[bg])
            for i in range(0, len(s), 70):
                f.write(s[i : i + 70] + "\n")
    return str(path)


# ---------------------------------------------------------------------------
# RecordPrefetcher unit behavior


def test_prefetcher_preserves_order_and_items():
    items = [(f"r{i}", np.arange(i + 1)) for i in range(23)]
    with RecordPrefetcher(iter(items), depth=3) as pf:
        got = list(pf)
    assert [g[0] for g in got] == [i[0] for i in items]
    for (_, a), (_, b) in zip(got, items):
        np.testing.assert_array_equal(a, b)
    assert not _prefetch_threads()


def test_prefetcher_propagates_producer_exception():
    def gen():
        yield ("a", 1)
        yield ("b", 2)
        raise RuntimeError("bad FASTA byte")

    pf = RecordPrefetcher(gen(), depth=2)
    assert next(pf)[0] == "a"
    assert next(pf)[0] == "b"
    with pytest.raises(RuntimeError, match="bad FASTA byte"):
        next(pf)
    assert not _prefetch_threads()


def test_prefetcher_close_joins_thread_midstream():
    """Abandoning the stream mid-file (e.g. a pipeline error) still joins
    the producer — no daemon thread leaks into the next test module."""
    produced = []

    def gen():
        for i in range(1000):
            produced.append(i)
            yield ("r", i)

    pf = RecordPrefetcher(gen(), depth=2)
    next(pf)
    pf.close()
    assert not _prefetch_threads()
    # Bounded lookahead: the producer never ran far past the queue depth.
    assert len(produced) <= 2 + 2


def test_prefetcher_bounded_queue_blocks_producer():
    def gen():
        for i in range(100):
            yield ("r", i)

    pf = RecordPrefetcher(gen(), depth=2)
    time.sleep(0.3)  # producer fills the queue, then must block
    assert pf._q.qsize() <= 2
    list(pf)
    assert not _prefetch_threads()


def test_maybe_prefetch_serial_passthrough():
    it = iter([1, 2, 3])
    out, close = maybe_prefetch(it, 0, "x")
    assert out is it
    close()  # no-op
    assert not _prefetch_threads()


def test_prefetcher_emits_obs_stream_event():
    with obs.observe() as ob:
        with RecordPrefetcher(iter([("a", 1), ("b", 2)]), depth=2, name="t") as pf:
            list(pf)
    ev = [e for e in ob.events if e["event"] == "prefetch_stream"]
    assert len(ev) == 1
    assert ev[0]["stream"] == "t"
    assert ev[0]["records"] == 2
    assert {"produce_s", "stall_s", "overlap_ratio", "max_depth"} <= set(ev[0])


# ---------------------------------------------------------------------------
# pipeline parity: overlapped vs serial


@pytest.mark.parametrize("island_engine", ["host", "device"])
def test_decode_overlapped_bit_identical(tmp_path, rng, island_engine):
    """Overlapped decode (record prefetch + span double-buffering +
    deferred call-column fetch) emits byte-identical island records."""
    import io

    fa = _write_fasta(tmp_path / "g.fa", rng)

    def run(prefetch):
        out = io.StringIO()
        pipeline.decode_file(
            fa, presets.durbin_cpg8(), islands_out=out, compat=False,
            span=2048, island_engine=island_engine, prefetch=prefetch,
        )
        return out.getvalue()

    serial = run(0)
    overlapped = run(3)
    assert serial == overlapped
    assert serial.count("\n") >= 3  # the comparison is not vacuous
    assert not _prefetch_threads()


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
@pytest.mark.parametrize("island_engine", ["host", "device"])
def test_posterior_overlapped_bit_identical(tmp_path, rng, island_engine):
    import io

    fa = _write_fasta(tmp_path / "p.fa", rng)

    def run(prefetch):
        out = io.StringIO()
        res = pipeline.posterior_file(
            fa, presets.durbin_cpg8(), islands_out=out, span=2048,
            island_engine=island_engine, prefetch=prefetch,
        )
        return out.getvalue(), res.mean_island_confidence

    (s_txt, s_conf) = run(0)
    (o_txt, o_conf) = run(3)
    assert s_txt == o_txt
    assert s_conf == o_conf
    assert not _prefetch_threads()


def test_decode_overlapped_with_confidence_and_paths(tmp_path, rng):
    """Host-islands clean decode with a state-path dump under prefetch:
    per-symbol outputs match the serial run exactly."""
    fa = _write_fasta(tmp_path / "s.fa", rng, n_records=4)
    outs = {}
    for tag, depth in (("serial", 0), ("overlapped", 2)):
        p = tmp_path / f"{tag}.npy"
        pipeline.decode_file(
            fa, presets.durbin_cpg8(), compat=False, span=2048,
            state_path_out=str(p), island_engine="host", prefetch=depth,
        )
        outs[tag] = np.load(p)
    np.testing.assert_array_equal(outs["serial"], outs["overlapped"])
    assert not _prefetch_threads()


def test_overlapped_adds_no_dispatches_telemetry_off(tmp_path, rng):
    """With telemetry OFF, the overlap machinery issues no device dispatch
    of its own: a raw ledger (counting only the blocking jax APIs) sees the
    overlapped run pay no more than the serial run."""
    import io

    from cpgisland_tpu.obs import ledger as ledger_mod

    fa = _write_fasta(tmp_path / "d.fa", rng, n_records=5)

    def run(prefetch):
        out = io.StringIO()
        pipeline.decode_file(
            fa, presets.durbin_cpg8(), islands_out=out, compat=False,
            span=2048, island_engine="device", prefetch=prefetch,
        )
        return out.getvalue()

    run(0)  # warm compiles
    counts = {}
    for tag, depth in (("serial", 0), ("overlapped", 3)):
        led = ledger_mod.Ledger()
        un = ledger_mod.install(led)
        try:
            run(depth)
        finally:
            un()
        counts[tag] = led.dispatches
    # Deferring fetches can only REMOVE blocking calls (the per-record
    # block_until_ready) — never add them.
    assert counts["overlapped"] <= counts["serial"], counts
    assert not _prefetch_threads()


def test_decode_overlapped_cap_overflow_retry(tmp_path, rng):
    """Cap overflow surfaces at the DEFERRED fetch; the retry re-dispatches
    at the grown cap and the emitted calls still match the serial path."""
    import io

    fa = _write_fasta(tmp_path / "c.fa", rng, n_records=5)

    def run(prefetch, cap):
        out = io.StringIO()
        pipeline.decode_file(
            fa, presets.durbin_cpg8(), islands_out=out, compat=False,
            span=2048, island_engine="device", island_cap=cap,
            prefetch=prefetch,
        )
        return out.getvalue()

    serial = run(0, None)
    n_calls = serial.count("\n")
    assert n_calls > 2
    overlapped_tiny_cap = run(3, 1)  # every record overflows cap=1
    assert overlapped_tiny_cap == serial
    assert not _prefetch_threads()
