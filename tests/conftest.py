"""Test configuration: force an 8-device virtual CPU platform BEFORE jax imports.

This stands in for a TPU pod slice in CI (SURVEY.md §4 "Distributed tests
without a cluster"): `shard_map`/`psum` code paths run unchanged on 8 fake CPU
devices here and on real chips in production.
"""

import os

# Unconditional: the environment may pre-set JAX_PLATFORMS to a TPU platform
# (and the axon plugin overrides the env var), but the test suite is defined to
# run on the virtual CPU mesh (override with CPGISLAND_TEST_PLATFORM to test on
# real hardware).  XLA_FLAGS must be set before jax initializes its backends;
# jax.config wins over the plugin's platform selection.
_platform = os.environ.get("CPGISLAND_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", _platform)

import numpy as np
import pytest

# On the DEFAULT (virtual CPU) platform the 8-device mesh is a hard
# requirement: if it silently came up with fewer devices, every
# require_devices(8) test would skip and CI would go green with the entire
# SPMD/shard_map path unexercised.  Fail loudly here instead.
if _platform == "cpu" and len(jax.devices()) < 8:
    raise RuntimeError(
        f"virtual CPU mesh broken: expected >= 8 devices, got "
        f"{len(jax.devices())} (XLA_FLAGS={os.environ.get('XLA_FLAGS')!r})"
    )


@pytest.fixture(scope="module", autouse=True)
def _fresh_compile_caches_per_module():
    """Full-suite runs (~400 tests' live executables in one single-core
    process) segfault inside XLA:CPU backend_compile at a LATE big compile —
    observed three times in r5, each at whatever large-program module ran
    ~90% in (test_viterbi_parallel twice, then test_viterbi_pallas after a
    single-module fixture moved the boundary); every file is green
    standalone with 125 GB free.  Dropping the accumulated executables at
    every module boundary keeps the in-process compile population small
    enough that the roving compiler-state crash never triggers.  CPU-only:
    the crash is XLA:CPU's, and on the relayed TPU every dropped executable
    would re-pay a remote compile."""
    if jax.default_backend() != "tpu":
        jax.clear_caches()
    yield


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def require_devices(n: int) -> None:
    """Skip the calling test when fewer than n devices exist — real-hardware
    runs (CPGISLAND_TEST_PLATFORM=axon) commonly have a single chip.  On the
    default virtual CPU platform a short mesh is a hard import-time error
    above, so this never silently skips there."""
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices, have {len(jax.devices())}")


def tpu_atol(tight: float, tpu: float = 5e-5) -> float:
    """Platform-keyed absolute tolerance: exact-ish on CPU (keeps regression
    sensitivity in CI), widened on TPU whose transcendentals are ~2e-5
    relative."""
    return tpu if jax.default_backend() == "tpu" else tight
