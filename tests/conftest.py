"""Test configuration: force an 8-device virtual CPU platform BEFORE jax imports.

This stands in for a TPU pod slice in CI (SURVEY.md §4 "Distributed tests
without a cluster"): `shard_map`/`psum` code paths run unchanged on 8 fake CPU
devices here and on real chips in production.
"""

import os

# Unconditional: the environment may pre-set JAX_PLATFORMS to a TPU platform
# (and the axon plugin overrides the env var), but the test suite is defined to
# run on the virtual CPU mesh (override with CPGISLAND_TEST_PLATFORM to test on
# real hardware).  XLA_FLAGS must be set before jax initializes its backends;
# jax.config wins over the plugin's platform selection.
_platform = os.environ.get("CPGISLAND_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", _platform)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def require_devices(n: int) -> None:
    """Skip the calling test when fewer than n devices exist — the suite
    normally runs on the 8-device virtual CPU mesh, but can be pointed at
    real hardware (CPGISLAND_TEST_PLATFORM=axon) where a single chip is the
    common case."""
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices, have {len(jax.devices())}")
