"""Test configuration: force an 8-device virtual CPU platform BEFORE jax imports.

This stands in for a TPU pod slice in CI (SURVEY.md §4 "Distributed tests
without a cluster"): `shard_map`/`psum` code paths run unchanged on 8 fake CPU
devices here and on real chips in production.
"""

import os

# Unconditional: the environment may pre-set JAX_PLATFORMS to a TPU platform
# (and the axon plugin overrides the env var), but the test suite is defined to
# run on the virtual CPU mesh (override with CPGISLAND_TEST_PLATFORM to test on
# real hardware).  XLA_FLAGS must be set before jax initializes its backends;
# jax.config wins over the plugin's platform selection.
_platform = os.environ.get("CPGISLAND_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", _platform)

import re

import numpy as np
import pytest

# On the DEFAULT (virtual CPU) platform the 8-device mesh is a hard
# requirement: if it silently came up with fewer devices, every
# require_devices(8) test would skip and CI would go green with the entire
# SPMD/shard_map path unexercised.  Fail loudly here instead.
if _platform == "cpu" and len(jax.devices()) < 8:
    raise RuntimeError(
        f"virtual CPU mesh broken: expected >= 8 devices, got "
        f"{len(jax.devices())} (XLA_FLAGS={os.environ.get('XLA_FLAGS')!r})"
    )


# Count true cache-miss XLA compiles at the same funnel the obs ledger uses
# (jax._src.compiler.backend_compile — in-memory cache hits never reach it).
# Installed once at conftest import, never uninstalled; obs Ledgers that
# install later wrap THIS wrapper and restore back to it, so the two
# coexist.  The counter drives the thresholded cache clear below.
_compiles_since_clear = [0]


def _install_compile_counter() -> None:
    from jax._src import compiler as _compiler

    orig_bc = _compiler.backend_compile

    def _counting_backend_compile(*a, **k):
        _compiles_since_clear[0] += 1
        return orig_bc(*a, **k)

    _compiler.backend_compile = _counting_backend_compile


_install_compile_counter()

# Live-executable population past which the per-module clear fires.  The
# r5 XLA:CPU segfault tracked ACCUMULATED executables (~400 tests' worth,
# crashing at a late big compile; every file green standalone) — the r5
# fix cleared at EVERY module boundary, costing ~2 min of suite wall
# re-tracing/re-compiling warm fixtures (VERDICT r5 #6).  Thresholding
# keeps the population bounded by (threshold + one module's compiles),
# an order of magnitude under the crash regime, while light modules skip
# the clear entirely and keep their warm caches.
_CLEAR_CACHES_COMPILE_THRESHOLD = 40


@pytest.fixture(scope="module", autouse=True)
def _fresh_compile_caches_per_module():
    """Full-suite runs (~400 tests' live executables in one single-core
    process) segfault inside XLA:CPU backend_compile at a LATE big compile —
    observed three times in r5, each at whatever large-program module ran
    ~90% in (test_viterbi_parallel twice, then test_viterbi_pallas after a
    single-module fixture moved the boundary); every file is green
    standalone with 125 GB free.  Dropping the accumulated executables
    keeps the in-process compile population small enough that the roving
    compiler-state crash never triggers; the clear is THRESHOLDED on the
    compile count since the last clear (r6) so light modules keep their
    warm caches and the suite buys back most of the blanket-clear wall.
    CPU-only: the crash is XLA:CPU's, and on the relayed TPU every dropped
    executable would re-pay a remote compile."""
    if (
        jax.default_backend() != "tpu"
        and _compiles_since_clear[0] >= _CLEAR_CACHES_COMPILE_THRESHOLD
    ):
        jax.clear_caches()
        _compiles_since_clear[0] = 0
    yield


# On-TPU skips must be SELF-JUSTIFYING (VERDICT r5 #4): the TPU suite
# artifact is captured with -q -rs (see CLAUDE.md), and every skip must
# carry a reason from this registry of known-legitimate classes —
# device-count guards, platform-scoped coverage, host capabilities, and
# artifact presence.  Any other on-TPU skip FAILS the test, so "skipped:
# TPU path quietly disabled" can never hide inside a green artifact.
_TPU_SKIP_ALLOWED = tuple(re.compile(p) for p in (
    r"needs \d+ devices?, have \d+",          # require_devices guards
    r"off-TPU expectation test",              # CPU-twin contract fixtures
    r"CPU-suite coverage",                    # compile-diversity fuzz
    r"device-count contract applies to the virtual CPU mesh",
    r"CPU backend lacks multi-process",       # host-jax capability
    r"native library unavailable",            # no C++ toolchain on host
    r"host-callback probe failed",            # jax host-callback capability
    r"no driver BENCH_r\*\.json present",     # artifact presence
    r"capture r\d+ is newer than the driver record",
    r"session-wide LockTracker active",   # CPGISLAND_TRACKSYNC=1 runs
))


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    rep = outcome.get_result()
    if (
        rep.skipped
        and not hasattr(rep, "wasxfail")
        and jax.default_backend() == "tpu"
    ):
        reason = (
            rep.longrepr[2] if isinstance(rep.longrepr, tuple)
            else str(rep.longrepr)
        )
        if not any(p.search(reason) for p in _TPU_SKIP_ALLOWED):
            rep.outcome = "failed"
            rep.longrepr = (
                f"unexplained on-TPU skip: {reason!r} — on-TPU skips must "
                "match a pattern in tests/conftest.py::_TPU_SKIP_ALLOWED "
                "(device-count / platform-scoped / host-capability / "
                "artifact-presence); add the new class there with a "
                "justification or unskip the test"
            )


@pytest.fixture(scope="session", autouse=True)
def _tracksync_session_tracker():
    """``CPGISLAND_TRACKSYNC=1``: run the whole suite under the graftsync
    runtime lock tracker (analysis/tracksync.py) — every lock created
    during the session is order-recorded, and the session FAILS at teardown
    on any observed lock-order cycle or guarded-access violation.  Opt-in:
    the wrappers cost a few percent of suite wall, and the per-test mux
    stress installs its own tracker when this one is absent."""
    if os.environ.get("CPGISLAND_TRACKSYNC") != "1":
        yield
        return
    from cpgisland_tpu.analysis import tracksync

    tracker, uninstall = tracksync.install()
    yield
    uninstall()
    tracker.assert_clean()


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def require_devices(n: int) -> None:
    """Skip the calling test when fewer than n devices exist — real-hardware
    runs (CPGISLAND_TEST_PLATFORM=axon) commonly have a single chip.  On the
    default virtual CPU platform a short mesh is a hard import-time error
    above, so this never silently skips there."""
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices, have {len(jax.devices())}")


def tpu_atol(tight: float, tpu: float = 5e-5) -> float:
    """Platform-keyed absolute tolerance: exact-ish on CPU (keeps regression
    sensitivity in CI), widened on TPU whose transcendentals are ~2e-5
    relative."""
    return tpu if jax.default_backend() == "tpu" else tight
