"""graftfault chaos harness (PR 15): every fleet failover path exercised by
seeded, deterministic fault plans on the 8-virtual-device mesh, with output
BIT-IDENTICAL to the fault-free run, zero dropped admitted requests, and
the requeue/replay paths ledger-asserted.

Layers:

- unit: the DeviceHealth state machine (healthy -> suspect -> quarantined
  -> half-open probe -> restored) on an injected clock; FaultPlan ordinal/
  match semantics; the two-phase manifest journal; the breaker's ``now_fn``.
- pool: a staged deterministic failover scenario (device faults past the
  retry budget mid-flush -> quarantine -> requeue onto the only other
  device -> probe -> restore), phantom-result quarantine, and the
  never-kill slow-dispatch quarantine (the slow flush's results are
  DELIVERED; only future flushes route away).
- crash: SIGKILL (simulated — BaseException, nothing between the injection
  point and this harness may catch it) planted at each journal phase
  boundary; a restarted broker replays completed requests bit-identically
  with zero duplicate device work and re-executes admitted-but-incomplete
  ones.
- wire: a connection dying mid-stream under the socket mux, recovered by
  the client's reconnect-with-replay.
- matrix: the seeded plan matrix (``faultplan.matrix``) swept over several
  seeds — interleaving-invariant assertions only (bit-identity, no drops,
  every injection ledgered).
"""

import json
import threading
import time

import numpy as np
import pytest

from cpgisland_tpu import obs, pipeline, resilience
from cpgisland_tpu.models import presets
from cpgisland_tpu.resilience import RetryPolicy, faultplan
from cpgisland_tpu.resilience.faultplan import Fault, FaultPlan, ManualClock
from cpgisland_tpu.serve import (
    BrokerConfig,
    DevicePool,
    FleetConfig,
    RequestBroker,
    Session,
)
from cpgisland_tpu.serve.fleet import DeviceHealth

FAST = RetryPolicy(backoff_base_s=0.0)  # max_retries=3 -> 4 attempts/unit
ATTEMPTS = FAST.max_retries + 1


@pytest.fixture(autouse=True)
def _fresh_resilience_state():
    resilience.reset()  # also disarms any leaked graftfault plan
    yield
    resilience.reset()


def _gen_symbols(rng, n: int) -> np.ndarray:
    bg = rng.choice(4, size=n, p=[0.3, 0.2, 0.2, 0.3])
    k = max(1, n // 4)
    bg[:k] = rng.choice(4, size=k, p=[0.1, 0.4, 0.4, 0.1])
    return bg.astype(np.uint8)


def _requests(seed=7, n=8):
    rng = np.random.default_rng(seed)
    return [
        (
            i,
            f"rec{i}",
            "decode" if i % 3 else "posterior",
            _gen_symbols(rng, 600 + 137 * i),
        )
        for i in range(n)
    ]


def _calls_key(calls) -> list:
    if calls is None:
        return []
    return [
        (int(calls.beg[i]), int(calls.end[i]), int(calls.length[i]),
         float(calls.gc_content[i]), float(calls.oe_ratio[i]))
        for i in range(len(calls))
    ]


def _result_key(r) -> tuple:
    return (r.kind, _calls_key(r.calls),
            None if r.conf_sum is None else float(r.conf_sum).hex())


def _assert_results_identical(got: dict, want: dict) -> None:
    assert set(got) == set(want)
    for rid in want:
        assert got[rid].ok, (rid, got[rid].error)
        assert _result_key(got[rid]) == _result_key(want[rid]), rid


# ---------------------------------------------------------------------------
# Unit: DeviceHealth state machine on an injected clock


def test_device_health_full_cycle_on_manual_clock():
    clock = ManualClock()
    h = DeviceHealth("devX", fault_threshold=3, cooldown_s=30.0,
                     now_fn=clock)
    assert h.state() == "healthy" and h.can_serve()
    h.record_fault(RuntimeError("f1"))
    assert h.state() == "suspect" and h.can_serve()
    h.record_success()
    assert h.state() == "healthy"  # suspicion clears on success
    for i in range(3):
        h.record_fault(RuntimeError(f"f{i}"))
    assert h.state() == "quarantined"
    assert not h.can_serve()  # cooldown not elapsed
    clock.advance(29.0)
    assert not h.can_serve()
    clock.advance(1.5)
    assert h.can_serve()  # flips to the half-open probe
    assert h.state() == "probing"
    assert h.can_serve()  # idempotent: the owner thread's next flush IS
    assert h.state() == "probing"  # the probe; no second thread exists
    h.record_success()
    assert h.state() == "healthy" and h.can_serve()
    assert h.snapshot()["restores"] == 1


def test_device_health_probe_failure_requarantines():
    clock = ManualClock()
    h = DeviceHealth("devX", fault_threshold=1, cooldown_s=10.0,
                     now_fn=clock)
    h.record_fault(RuntimeError("boom"))
    assert h.state() == "quarantined"
    clock.advance(11.0)
    assert h.can_serve()  # probe admitted
    h.record_fault(RuntimeError("probe boom"))
    assert h.state() == "quarantined"  # fresh cooldown
    assert not h.can_serve()
    clock.advance(11.0)
    assert h.can_serve()
    assert h.snapshot()["quarantines"] == 2


def test_device_health_phantom_and_slow_thresholds():
    from cpgisland_tpu.resilience.sentinel import PhantomResult

    h = DeviceHealth("devP", fault_threshold=10, phantom_threshold=2,
                     now_fn=ManualClock())
    h.record_fault(PhantomResult("stale"))
    assert h.state() == "suspect"
    h.record_fault(PhantomResult("stale again"))
    assert h.state() == "quarantined"  # phantoms trip sooner than faults

    h2 = DeviceHealth("devS", slow_threshold=2, now_fn=ManualClock())
    h2.record_slow(400.0)
    assert h2.state() == "healthy"  # slow alone never fails the attempt
    h2.record_slow(500.0)
    assert h2.state() == "quarantined"  # quarantined, never killed


def test_device_health_strikes_reset_on_fast_success():
    """Slow/phantom strikes count CONSECUTIVE evidence: a fast healthy
    dispatch in between resets them, so isolated transients days apart
    (CLAUDE.md's occasional ~20x slowdowns) can never accumulate into a
    quarantine."""
    from cpgisland_tpu.resilience.sentinel import PhantomResult

    h = DeviceHealth("devR", slow_threshold=2, phantom_threshold=2,
                     fault_threshold=10, now_fn=ManualClock())
    h.record_slow(400.0)
    h.record_success()  # fast success between the two slow dispatches
    h.record_slow(400.0)
    assert h.state() == "healthy"
    h.record_fault(PhantomResult("stale"))
    h.record_success()
    h.record_fault(PhantomResult("stale"))
    assert h.state() == "suspect"  # never two CONSECUTIVE phantoms


def test_breaker_takes_now_fn_alias():
    clock = ManualClock()
    br = resilience.EngineBreaker(threshold=1, cooldown_s=20.0, now_fn=clock)
    br.record_fault("decode.onehot")
    assert br.tripped("decode.onehot")
    clock.advance(21.0)
    assert br.allowed("decode.onehot")  # half-open probe, no sleeping


# ---------------------------------------------------------------------------
# Unit: FaultPlan semantics


def test_faultplan_ordinals_match_and_ledger():
    plan = FaultPlan(
        [Fault("p", kind="fault", nth=2, times=2, match="devA")],
        name="unit",
    )
    with faultplan.active(plan):
        faultplan.check("p", tag="devB:x")  # match filter: not counted
        faultplan.check("p", tag="devA:x")  # arrival 1: below nth
        for _ in range(2):  # arrivals 2, 3: fire
            with pytest.raises(RuntimeError, match="graftfault"):
                faultplan.check("p", tag="devA:x")
        faultplan.check("p", tag="devA:x")  # arrival 4: window passed
    assert [f["arrival"] for f in plan.injected] == [2, 3]
    # Disarmed: zero-cost no-op.
    faultplan.check("p", tag="devA:x")


def test_faultplan_slow_pads_and_kill_is_baseexception():
    plan = FaultPlan([
        Fault("w.wall", kind="slow", nth=1, pad_s=123.0),
        Fault("k", kind="kill", nth=1),
    ])
    with faultplan.active(plan):
        assert faultplan.wall_pad("w.wall", tag="t") == 123.0
        assert faultplan.wall_pad("w.wall", tag="t") == 0.0
        with pytest.raises(faultplan.SimulatedKill):
            try:
                faultplan.check("k")
            except Exception:  # noqa: BLE001 - the point: Exception misses it
                pytest.fail("SimulatedKill must not be caught by Exception")


def test_double_arm_rejected():
    plan = FaultPlan([Fault("p")])
    with faultplan.active(plan):
        with pytest.raises(RuntimeError, match="already armed"):
            faultplan.arm(FaultPlan([Fault("q")]))


# ---------------------------------------------------------------------------
# Unit: the two-phase admission journal


def test_manifest_two_phase_journal_roundtrip(tmp_path):
    from cpgisland_tpu.resilience.manifest import RunManifest

    path = str(tmp_path / "j.jsonl")
    header = {"mode": "serve", "params": "x"}
    m = RunManifest(path, header=header, resume=False)
    m.record_admitted(1, "k1", 100, payload={"tenant": "a", "kind": "decode",
                                             "name": "r1", "model": "",
                                             "symbols": ""})
    m.record_admitted(2, "k2", 200, payload={"tenant": "a", "kind": "decode",
                                             "name": "r2", "model": "",
                                             "symbols": ""})
    m.record_done(1, "k1", 100)
    m.close()  # the admit for 2 has no completion: re-execution due

    m2 = RunManifest(path, header=header, resume=True)
    pend = m2.admitted_incomplete()
    assert [rec["index"] for rec in pend] == [2]
    assert m2.completed(1, "k1", 100) is not None
    assert m2.n_completed() == 1
    # Completion resolves the admit: the payload leaves memory (a
    # long-lived daemon must not retain every request's input forever).
    assert 1 not in m2._admitted
    # A mismatched probe with discard_mismatch=False must NOT destroy the
    # stored completion (the in-life duplicate-collision path).
    assert m2.completed(1, "OTHER", 999, discard_mismatch=False) is None
    assert m2.completed(1, "k1", 100) is not None
    # Idempotent re-admit of a journaled id is a no-op (no duplicate line).
    m2.record_admitted(2, "k2", 200, payload={})
    m2.close()
    lines = [json.loads(ln) for ln in open(path)]
    assert sum(1 for ln in lines if ln.get("kind") == "admit"
               and ln["index"] == 2) == 1


def test_reused_id_admit_supersedes_stale_completion(tmp_path):
    """An id completed as A in life 1, then discarded-and-re-admitted as B
    in life 2 (identity mismatch), then crashed: life 3 must re-execute B
    — the stale on-disk completion of A must not shadow B's admit out of
    the restart set (and must not replay as A either)."""
    from cpgisland_tpu.resilience.manifest import RunManifest

    path = str(tmp_path / "r.jsonl")
    header = {"mode": "serve", "params": "x"}
    m1 = RunManifest(path, header=header, resume=False)
    m1.record_admitted(7, "A", 100, payload={"v": "a"})
    m1.record_done(7, "A", 100)
    m1.close()

    m2 = RunManifest(path, header=header, resume=True)
    assert m2.completed(7, "B", 200) is None  # mismatch: discards A
    m2.record_admitted(7, "B", 200, payload={"v": "b"})
    m2.close()  # crash before B completes (nothing else written)

    m3 = RunManifest(path, header=header, resume=True)
    pend = m3.admitted_incomplete()
    assert [(r["index"], r["name"]) for r in pend] == [(7, "B")]
    assert m3.completed(7, "A", 100) is None  # A's record is superseded


def test_completed_id_resubmission_replays_not_duplicate(tmp_path):
    """A reconnecting client re-submits an id whose first life COMPLETED
    (the response died with the connection): that must REPLAY from the
    manifest — hitting the duplicate-id rejection instead would livelock
    the client's retry loop forever."""
    params = presets.durbin_cpg8()
    sess = Session(params, name="t", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 20, flush_deadline_s=0.0),
        manifest_path=str(tmp_path / "m.jsonl"),
    )
    syms = _gen_symbols(np.random.default_rng(3), 700)
    broker.submit(request_id=9, tenant="a", kind="decode", symbols=syms,
                  name="r9")
    (first,) = broker.drain()
    assert first.ok and not first.replayed
    # Same process life, same id, after completion: replay, not reject.
    broker.submit(request_id=9, tenant="a", kind="decode", symbols=syms,
                  name="r9")
    (again,) = broker.drain()
    assert again.replayed and again.route == "replay"
    assert _result_key(again)[1] == _result_key(first)[1]
    # A duplicate of a QUEUED (not completed) id still rejects.
    broker.submit(request_id=10, tenant="a", kind="decode", symbols=syms,
                  name="r10")
    with pytest.raises(ValueError, match="duplicate request id"):
        broker.submit(request_id=10, tenant="a", kind="decode",
                      symbols=syms, name="r10")
    broker.drain()
    broker.close()


def test_failed_request_resolves_admit_and_rejournals_on_reuse(
    tmp_path, monkeypatch
):
    """A FAILED request writes a terminal 'fail' journal line: restarts do
    not re-execute known-failing requests, and a reused id journals a
    FRESH admit with the NEW payload (which a crash then re-executes)."""
    params = presets.durbin_cpg8()
    mpath = str(tmp_path / "m.jsonl")
    cfg = BrokerConfig(flush_symbols=1 << 20, flush_deadline_s=0.0)
    sess = Session(params, name="t", retry_policy=FAST,
                   private_breaker=True)
    b1 = RequestBroker(sess, cfg, manifest_path=mpath)
    state = {"fail": True}
    orig_run = sess.supervisor.run

    def run(thunk, **kw):
        if state["fail"]:
            raise RuntimeError("persistent injected fault")
        return orig_run(thunk, **kw)

    monkeypatch.setattr(sess.supervisor, "run", run)
    rng = np.random.default_rng(5)
    syms_a = _gen_symbols(rng, 600)
    b1.submit(request_id=4, tenant="a", kind="decode", symbols=syms_a,
              name="A")
    (failed,) = b1.drain()
    assert not failed.ok
    # The admit is RESOLVED: nothing left for a restart to re-execute.
    assert b1.manifest.admitted_incomplete() == []
    # Reuse the id for a DIFFERENT record; crash before it flushes.
    state["fail"] = False
    syms_b = _gen_symbols(rng, 900)
    b1.submit(request_id=4, tenant="a", kind="decode", symbols=syms_b,
              name="B")
    # (abandon b1 without drain/close: the crash)

    sess2 = Session(params, name="t2", private_breaker=True)
    b2 = RequestBroker(sess2, cfg, manifest_path=mpath, resume=True)
    reexec = {r.id: r for r in b2.drain()}
    # The restart re-executes B's payload (the fresh admit), not A's.
    assert sorted(reexec) == [4]
    assert reexec[4].ok and reexec[4].n_symbols == syms_b.size
    b2.close()


# ---------------------------------------------------------------------------
# Pool scenarios (staged for determinism: the only healthy device is the
# one the plan targets, so WHICH worker takes the flush is pinned)


def _run_pool(recs, *, plan=None, n_devices=2, stage=None,
              timeout_s=300.0):
    """Run ``recs`` through a DevicePool; returns ({id: result}, pool,
    observed events).  ``stage(pool, clock)`` runs after construction but
    before traffic (force-quarantines etc.); the pool is stopped+closed
    before returning.  Health cooldowns run on a ManualClock the wait
    loop advances, so parked workers probe without real waiting."""
    params = presets.durbin_cpg8()
    sess = Session(params, name="chaos", private_breaker=True,
                   retry_policy=FAST)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1500, flush_deadline_s=0.01)
    )
    clock = ManualClock()
    cfg = FleetConfig(cooldown_s=30.0, now_fn=clock)
    pool = DevicePool.build(broker, n_devices=n_devices, config=cfg)
    results: dict = {}
    done = threading.Event()

    def on_result(r):
        results[r.id] = r
        if len(results) >= len(recs):
            done.set()

    if stage is not None:
        stage(pool, clock)
    ctx = faultplan.active(plan) if plan is not None else None
    if ctx is not None:
        ctx.__enter__()
    try:
        with obs.observe() as ob:
            pool.start(on_result)
            for rid, nm, kind, syms in recs:
                broker.submit(request_id=rid, tenant="a", kind=kind,
                              symbols=syms, name=nm)
            # Requeued flushes may be parked behind a quarantine cooldown:
            # keep advancing the injected clock until everything lands.
            deadline = time.monotonic() + timeout_s
            while not done.wait(timeout=0.25):
                assert time.monotonic() < deadline, (
                    f"undelivered: {sorted(set(r[0] for r in recs) - set(results))}, "
                    f"stats={pool.stats()}"
                )
                clock.advance(5.0)
    finally:
        pool.stop()
        pool.close()
        broker.close()
        if ctx is not None:
            ctx.__exit__(None, None, None)
    return results, pool, list(ob.events)


@pytest.mark.slow
def test_device_fault_mid_flush_fails_over_bit_identical():
    """The headline failover: dev0 (the only initially healthy device)
    faults past the supervisor's retry budget mid-flush -> quarantined;
    the flush requeues INTACT onto dev1 (admitted after its cooldown via
    the half-open probe) -> probe succeeds -> dev1 restored; final results
    bit-identical to the fault-free run, every re-dispatch and requeue
    ledgered, zero dropped requests."""
    recs = _requests()
    clean, _pool0, _ev0 = _run_pool(recs)
    assert all(r.ok for r in clean.values())

    def stage(pool, clock):
        # dev1 starts quarantined -> dev0 MUST take the first flush.
        pool.workers[1].health.force_quarantine("staged")

    plan = FaultPlan(
        [Fault("dispatch", kind="fault", match="@dev0", nth=1,
               times=ATTEMPTS)],
        name="dev0-faults",
    )
    chaos, pool, events = _run_pool(recs, plan=plan, stage=stage)
    _assert_results_identical(chaos, clean)

    # The chaos actually happened and was fully ledgered.
    injected = [e for e in events if e["event"] == "graftfault_injected"]
    assert len(injected) == ATTEMPTS
    faults = [e for e in events if e["event"] == "dispatch_fault"]
    assert len(faults) >= ATTEMPTS  # every injected attempt ledgered
    quar = [e for e in events if e["event"] == "device_quarantined"]
    assert any(e["device"] == "dev0" and e["reason"] == "faults"
               for e in quar)
    requeued = [e for e in events if e["event"] == "flush_requeued"]
    assert len(requeued) >= 1 and requeued[0]["device"] == "dev0"
    restored = [e for e in events if e["event"] == "device_restored"]
    assert any(e["device"] == "dev1" for e in restored)  # probe succeeded
    st = pool.stats()
    assert st["requeues"] >= 1 and st["failed_over"] >= 1
    assert st["pending_requeued"] == 0
    assert st["devices"]["dev0"]["quarantines"] >= 1


@pytest.mark.slow
def test_phantom_results_quarantine_and_fail_over():
    recs = _requests(seed=11, n=6)
    clean, _p, _e = _run_pool(recs)

    def stage(pool, clock):
        pool.workers[1].health.force_quarantine("staged")

    plan = FaultPlan(
        [Fault("dispatch", kind="phantom", match="@dev0", nth=1,
               times=ATTEMPTS)],
        name="dev0-phantoms",
    )
    chaos, _pool, events = _run_pool(recs, plan=plan, stage=stage)
    _assert_results_identical(chaos, clean)
    quar = [e for e in events if e["event"] == "device_quarantined"]
    # Phantoms trip at phantom_threshold (2) — before the plain-fault
    # threshold (3) would have.
    assert any(e["device"] == "dev0" and e["reason"] == "phantom"
               for e in quar)


@pytest.mark.slow
def test_slow_dispatch_quarantines_but_never_kills():
    """The never-kill rule as fleet policy: injected 600 s walls (no real
    sleeping — graftfault pads the measured wall) escalate dispatch_slow,
    the device is QUARANTINED for future flushes, but the slow flush's
    own results are delivered intact."""
    recs = _requests(seed=13, n=6)
    clean, _p, _e = _run_pool(recs)

    def stage(pool, clock):
        pool.workers[1].health.force_quarantine("staged")

    plan = FaultPlan(
        [Fault("dispatch.wall", kind="slow", match="@dev0", nth=1, times=2,
               pad_s=600.0)],
        name="dev0-slow",
    )
    chaos, _pool, events = _run_pool(recs, plan=plan, stage=stage)
    _assert_results_identical(chaos, clean)  # slow results still delivered
    slow = [e for e in events if e["event"] == "dispatch_slow"]
    assert len(slow) >= 2
    quar = [e for e in events if e["event"] == "device_quarantined"]
    assert any(e["device"] == "dev0" and e["reason"] == "slow"
               for e in quar)
    # No requeue: the slow flushes SUCCEEDED (nothing was killed).
    assert not any(e["event"] == "flush_requeued" for e in events)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_seeded_chaos_matrix_converges_bit_identical(seed):
    """The CI chaos matrix: seeded dispatch-level plans (fault past
    budget, phantom, single transient, slow) against a 2-device pool with
    no staging — interleaving-invariant assertions only: bit-identity,
    zero dropped admitted requests, every injection ledgered."""
    recs = _requests(seed=17, n=8)
    clean, _p, _e = _run_pool(recs)
    for plan in faultplan.matrix(seed, attempts=ATTEMPTS):
        chaos, _pool, events = _run_pool(recs, plan=plan)
        _assert_results_identical(chaos, clean)
        injected = [e for e in events
                    if e["event"] == "graftfault_injected"]
        assert len(injected) == len(plan.injected)


@pytest.mark.slow
def test_requeue_refused_without_a_plausible_taker_fails_loudly():
    """When no non-excluded device could serve within the requeue horizon
    (here: the other device is drained with an effectively-infinite
    cooldown), a faulted flush is NOT parked on the failover queue — its
    failures are delivered loudly and nothing hangs."""
    params = presets.durbin_cpg8()
    sess = Session(params, name="notaker", private_breaker=True,
                   retry_policy=FAST)
    # One flush holds the whole workload: after it fails over nowhere,
    # nothing else is queued behind two quarantined devices.
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 20, flush_deadline_s=0.01)
    )
    clock = ManualClock()
    pool = DevicePool.build(
        broker, n_devices=2,
        config=FleetConfig(cooldown_s=1e9, now_fn=clock),
    )
    recs = _requests(seed=41, n=3)
    results: dict = {}
    done = threading.Event()

    def on_result(r):
        results[r.id] = r
        if len(results) >= len(recs):
            done.set()

    pool.workers[1].health.force_quarantine("drained")
    plan = FaultPlan(
        [Fault("dispatch", kind="fault", match="@dev0", nth=1,
               times=10 * ATTEMPTS)],
        name="dev0-poisoned-no-taker",
    )
    with obs.observe() as ob:
        with faultplan.active(plan):
            try:
                pool.start(on_result)
                for rid, nm, kind, syms in recs:
                    broker.submit(request_id=rid, tenant="a", kind=kind,
                                  symbols=syms, name=nm)
                assert done.wait(timeout=120.0), (
                    f"hung: {sorted(results)}, {pool.stats()}"
                )
            finally:
                pool.stop()
                pool.close()
                broker.close()
    assert sorted(results) == [r[0] for r in recs]
    assert any(not r.ok for r in results.values())
    for r in results.values():
        if not r.ok:
            assert "graftfault" in (r.error or "")
    # Refused, not parked: no requeue event, nothing left on the queue.
    assert not any(e["event"] == "flush_requeued" for e in ob.events)
    assert pool.stats()["pending_requeued"] == 0


# ---------------------------------------------------------------------------
# Crash consistency: SIGKILL at each journal phase boundary

# (phase point, arrival ordinal, admits expected on disk after the kill,
# completions expected on disk after the kill) for a 4-request
# single-flush workload submitted in rid order 0..3.
_KILL_PHASES = [
    ("journal.pre_admit", 3, 2, 0),   # killed before accepting request #3
    ("journal.post_admit", 3, 3, 0),  # killed between journal and flush
    ("flush.enter", 1, 4, 0),         # killed mid-flush, pre-completion
    ("journal.pre_complete", 2, 4, 1),   # killed mid-completion loop
    ("journal.post_complete", 4, 4, 4),  # killed after the last completion
]


@pytest.mark.slow
@pytest.mark.parametrize("point,nth,n_admitted,n_completed", _KILL_PHASES)
def test_sigkill_at_journal_phase_restart_replays_bit_identical(
    tmp_path, point, nth, n_admitted, n_completed
):
    """SIGKILL (simulated) planted at each journal phase boundary: the
    restarted daemon re-executes admitted-but-incomplete requests itself
    (journal_replay), replays completed ones bit-identically with ZERO
    duplicate device work, and a client re-submitting every id converges
    to the fault-free output."""
    params = presets.durbin_cpg8()
    recs = _requests(seed=23, n=4)
    sizes = {rid: int(s.size) for rid, _nm, _k, s in recs}
    cfg = BrokerConfig(flush_symbols=1 << 20, flush_deadline_s=0.0)

    # Fault-free ground truth (no manifest).
    s0 = Session(params, name="clean", private_breaker=True)
    b0 = RequestBroker(s0, cfg)
    for rid, nm, kind, syms in recs:
        b0.submit(request_id=rid, tenant="a", kind=kind, symbols=syms,
                  name=nm)
    clean = {r.id: r for r in b0.drain()}
    assert all(r.ok for r in clean.values())

    # Life 1: killed at the phase boundary.  NOTHING is closed afterwards
    # (SIGKILL semantics) — what survives is what was flushed per line.
    mpath = str(tmp_path / "serve.journal.jsonl")
    s1 = Session(params, name="life1", private_breaker=True)
    b1 = RequestBroker(s1, cfg, manifest_path=mpath, resume=False)
    plan = FaultPlan([Fault(point, kind="kill", nth=nth)],
                     name=f"kill@{point}")
    killed = False
    with faultplan.active(plan):
        try:
            for rid, nm, kind, syms in recs:
                b1.submit(request_id=rid, tenant="a", kind=kind,
                          symbols=syms, name=nm)
            for r in b1.drain():
                pass
        except faultplan.SimulatedKill:
            killed = True
    assert killed, "the kill plan never fired"

    # Life 2: restart over the same journal.  Submissions are in rid
    # order, flush results complete in rid order, so the journal holds
    # the first n_admitted admits and the first n_completed completions.
    admitted_ids = {rid for rid, _nm, _k, _s in recs[:n_admitted]}
    incomplete = sorted(admitted_ids)[n_completed:]
    s2 = Session(params, name="life2", private_breaker=True)
    with obs.observe() as ob:
        b2 = RequestBroker(s2, cfg, manifest_path=mpath, resume=True)
        reexec = {r.id: r for r in b2.drain()}  # the journal re-queue
    replay_ev = [e for e in ob.events if e["event"] == "journal_replay"]
    if incomplete:
        assert replay_ev and replay_ev[0]["n_reexecuted"] == len(incomplete)
        assert replay_ev[0]["n_completed"] == n_completed
    assert sorted(reexec) == incomplete
    assert all(r.ok and not r.replayed for r in reexec.values())
    # Zero duplicate device work for completed records: only the
    # incomplete ones touched the device on restart.
    assert b2.flushed_symbols == sum(sizes[rid] for rid in incomplete)

    # The reconnecting client re-submits EVERY id: journaled ones replay
    # from the manifest (still zero device work), never-admitted ones
    # (pre-admit kill) execute fresh.
    for rid, nm, kind, syms in recs:
        b2.submit(request_id=rid, tenant="a", kind=kind, symbols=syms,
                  name=nm)
    final = {r.id: r for r in b2.drain()}
    for rid in admitted_ids:
        assert final[rid].replayed and final[rid].route == "replay", rid
    _assert_results_identical(final, clean)
    # Device work across life 2 = incomplete re-executions + fresh
    # never-admitted submissions; completed records cost zero.
    fresh = sorted(set(sizes) - admitted_ids)
    assert b2.flushed_symbols == sum(
        sizes[rid] for rid in list(incomplete) + fresh
    )
    b2.close()


@pytest.mark.slow
def test_shutdown_drain_completions_reach_journal(tmp_path):
    """The shutdown op stops ADMISSION (broker.close) but the transports
    drain admitted work afterwards — those completions must still land in
    the journal (the manifest closes at release(), after the drain), or a
    restarted daemon re-executes work it finished."""
    import io

    from cpgisland_tpu.serve import transport

    params = presets.durbin_cpg8()
    rng = np.random.default_rng(37)
    syms = _gen_symbols(rng, 800)
    lines = [
        json.dumps({"id": 1, "kind": "decode",
                    "seq": "".join(np.array(list("acgt"))[syms])}),
        json.dumps({"op": "shutdown"}),  # admitted work drains after this
    ]
    mpath = str(tmp_path / "m.jsonl")
    sess = Session(params, name="t", private_breaker=True)
    # Huge budget + deadline: the request is still QUEUED at shutdown, so
    # only the post-close drain can serve (and journal) it.
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 22, flush_deadline_s=60.0),
        manifest_path=mpath,
    )
    out = io.StringIO()
    transport.serve_stream(
        io.StringIO("\n".join(lines) + "\n"), out, broker, use_worker=False
    )
    broker.release()
    resp = [json.loads(ln) for ln in out.getvalue().splitlines()]
    assert resp and resp[0]["ok"] and not resp[0]["replayed"]
    kinds = [json.loads(ln).get("kind") for ln in open(mpath)]
    assert kinds.count("admit") == 1 and kinds.count("record") == 1, kinds

    # Restart: the completed request replays with zero device work.
    sess2 = Session(params, name="t2", private_breaker=True)
    b2 = RequestBroker(
        sess2, BrokerConfig(flush_symbols=1 << 22, flush_deadline_s=0.0),
        manifest_path=mpath, resume=True,
    )
    b2.submit(request_id=1, tenant="default", kind="decode", symbols=syms,
              name="req1")
    (r2,) = b2.drain()
    assert r2.replayed and b2.flushes == 0
    b2.close()
    b2.release()


# ---------------------------------------------------------------------------
# Wire: connection death mid-stream + client reconnect-with-replay


@pytest.mark.slow
def test_connection_death_mid_stream_client_replays(tmp_path):
    """graftfault kills the mux connection mid-stream (transport.read
    disconnect); tools/serve_client's reconnect-with-replay re-submits its
    incomplete ids and converges to the batch-pipeline output."""
    import os
    import socket as socket_mod
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    import serve_client

    from cpgisland_tpu.serve.transport import serve_socket

    params = presets.durbin_cpg8()
    rng = np.random.default_rng(29)
    names_syms = [(f"w{k}", _gen_symbols(rng, 700 + 120 * k))
                  for k in range(4)]
    # Batch-pipeline ground truth.
    bases = np.array(list("acgt"))
    fa = tmp_path / "w.fa"
    with open(fa, "w") as f:
        for nm, syms in names_syms:
            f.write(f">{nm}\n" + "".join(bases[syms]) + "\n")
    want = pipeline.decode_file(str(fa), params, compat=False)
    want_text: dict = {}
    for line in want.calls.format_lines().splitlines(keepends=True):
        want_text.setdefault(line.split(" ", 1)[0], []).append(line)

    sess = Session(params, name="wire", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 20, flush_deadline_s=0.05)
    )
    sock_path = str(tmp_path / "w.sock")
    server = threading.Thread(
        target=serve_socket, args=(sock_path, broker), daemon=True
    )
    server.start()
    deadline = time.monotonic() + 30.0
    while not os.path.exists(sock_path):
        assert time.monotonic() < deadline
        time.sleep(0.01)
    while True:
        try:
            s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
            s.connect(sock_path)
            s.close()
            break
        except OSError:
            assert time.monotonic() < deadline
            time.sleep(0.05)

    requests = [
        {"id": 100 + k, "kind": "decode", "seq": "".join(bases[syms]),
         "name": nm}
        for k, (nm, syms) in enumerate(names_syms)
    ]
    # The connection serving these dies before its 2nd request line is
    # even parsed; the client must reconnect and re-submit.
    plan = FaultPlan([Fault("transport.read", kind="disconnect", nth=2)],
                     name="conn-death")
    with faultplan.active(plan):
        responses = serve_client.run_socket_session(
            sock_path, requests, reconnects=5,
        )
    assert len(plan.injected) == 1  # the disconnect really fired
    assert set(responses) == {100, 101, 102, 103}
    for k, (nm, _syms) in enumerate(names_syms):
        resp = responses[100 + k]
        assert resp["ok"], resp.get("error")
        assert resp.get("islands_text", "") == "".join(
            want_text.get(nm, [])
        ), nm

    # Orderly shutdown.
    s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
    s.connect(sock_path)
    s.sendall(b'{"op": "shutdown"}\n')
    s.close()
    server.join(timeout=60.0)
    assert not server.is_alive()


# ---------------------------------------------------------------------------
# graftscope: chaos-certified fault visibility (PR 16).  Every injected
# fault must be ATTRIBUTABLE in telemetry — site, affected request ids,
# the requeue/failover decision — and the flight recorder must survive a
# SIGKILL at every journal phase.


@pytest.mark.slow
def test_injected_fault_attributable_in_recorder_and_lineage(tmp_path):
    """The headline failover with a graftscope Scope installed: the flight
    recorder names the injection site + plan, the quarantine, the requeue
    decision WITH the affected request ids, and the restore; the affected
    requests' traces show requeued-after-quarantine lineage (requeue hop
    on dev0, second flush membership on dev1, closed ok)."""
    from cpgisland_tpu.obs import scope as scope_mod

    recs = _requests()

    def stage(pool, clock):
        pool.workers[1].health.force_quarantine("staged")

    plan = FaultPlan(
        [Fault("dispatch", kind="fault", match="@dev0", nth=1,
               times=ATTEMPTS)],
        name="dev0-faults",
    )
    sc = scope_mod.install(
        scope_mod.Scope(flight_path=str(tmp_path / "f.flight.json"))
    )
    try:
        chaos, pool, _events = _run_pool(recs, plan=plan, stage=stage)
    finally:
        scope_mod.uninstall(sc)
    assert all(r.ok for r in chaos.values())

    ring = sc.recorder.snapshot()
    inj = [e for e in ring if e["kind"] == "graftfault_injected"]
    assert len(inj) == ATTEMPTS
    assert all(e["point"] == "dispatch" and e["plan"] == "dev0-faults"
               and e["fault_kind"] == "fault" for e in inj)
    quar = [e for e in ring if e["kind"] == "device_quarantined"]
    assert any(e["device"] == "dev0" and e["reason"] == "faults"
               for e in quar)
    rq = [e for e in ring if e["kind"] == "flush_requeued"]
    assert rq and rq[0]["device"] == "dev0"
    affected = set(rq[0]["request_ids"])
    assert affected and affected <= set(chaos)
    assert "graftfault" in rq[0]["error"]
    assert any(e["kind"] == "device_restored" and e["device"] == "dev1"
               for e in ring)

    # Requeued-after-quarantine lineage: the trace shows BOTH flush
    # memberships and attributes the request to the device that served it.
    traces = {tr["id"]: tr for tr in sc.traces}
    assert sorted(traces) == sorted(r[0] for r in recs)  # no drops
    for rid in affected:
        tr = traces[rid]
        hops = [h["hop"] for h in tr["hops"]]
        assert "requeue" in hops, (rid, hops)
        fe = [h for h in tr["hops"] if h["hop"] == "flush.enter"]
        assert len(fe) >= 2 and fe[0]["device"] == "dev0"
        assert fe[-1]["device"] == "dev1"
        assert hops[-1] == "respond" and tr["ok"]
        assert tr["device"] == "dev1"  # served-by, not faulted-by
        stamps = [h["t"] for h in tr["hops"]]
        assert stamps == sorted(stamps)

    # The postmortem artifact persists and renders.
    from cpgisland_tpu.obs import report

    path = sc.recorder.persist("test-shutdown")
    assert path is not None
    text = report.render_flight(path)
    assert "flush_requeued" in text and "device_quarantined" in text


@pytest.mark.slow
@pytest.mark.parametrize("point,nth", [(p, n) for p, n, _a, _c in _KILL_PHASES])
def test_flight_recorder_survives_sigkill_at_each_journal_phase(
    tmp_path, point, nth
):
    """SIGKILL planted at each journal phase boundary: the flight artifact
    is on disk BEFORE the kill propagates, names the kill site, and
    carries the injection event (site + per-request tag attribution)."""
    from cpgisland_tpu.obs import scope as scope_mod

    params = presets.durbin_cpg8()
    recs = _requests(seed=23, n=4)
    mpath = str(tmp_path / "serve.journal.jsonl")
    fpath = f"{mpath}.flight.json"
    sess = Session(params, name="killvis", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 20, flush_deadline_s=0.0),
        manifest_path=mpath, resume=False,
    )
    plan = FaultPlan([Fault(point, kind="kill", nth=nth)],
                     name=f"kill@{point}")
    sc = scope_mod.install(scope_mod.Scope(flight_path=fpath))
    killed = False
    try:
        with faultplan.active(plan):
            try:
                for rid, nm, kind, syms in recs:
                    broker.submit(request_id=rid, tenant="a", kind=kind,
                                  symbols=syms, name=nm)
                for _ in broker.drain():
                    pass
            except faultplan.SimulatedKill:
                killed = True
    finally:
        scope_mod.uninstall(sc)
    assert killed, "the kill plan never fired"
    dump = json.load(open(fpath))
    assert dump["reason"] == f"kill:{point}"
    kills = [e for e in dump["events"] if e["kind"] == "kill"]
    assert kills and kills[-1]["point"] == point
    inj = [e for e in dump["events"] if e["kind"] == "graftfault_injected"]
    assert inj and inj[-1]["fault_kind"] == "kill"
    assert inj[-1]["point"] == point and inj[-1]["tag"]
