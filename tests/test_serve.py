"""Serving subsystem (PR 8): session layer, broker parity vs the batch
pipelines, flush policy, admission control, per-session resilience,
daemon restart from PR 5 manifests, and the JSONL transport.

The headline test streams >= 16 heterogeneous records (mixed lengths,
decode + posterior, two tenants) through the in-process broker and pins
the results BIT-IDENTICAL to ``decode_file``/``posterior_file`` on the
same records, with the obs ledger proving zero fresh compiles and zero
prepared-cache re-preps after the first flush of each geometry.
"""

import io
import json
import threading
import time

import numpy as np
import pytest

from cpgisland_tpu import obs, pipeline, resilience
from cpgisland_tpu.models import presets
from cpgisland_tpu.resilience import RetryPolicy
from cpgisland_tpu.serve import (
    Backpressure,
    BrokerConfig,
    RequestBroker,
    ServeLoop,
    Session,
)

FAST = RetryPolicy(backoff_base_s=0.0)


@pytest.fixture(autouse=True)
def _fresh_resilience_state():
    resilience.reset()
    yield
    resilience.reset()


def _gen_symbols(rng, n: int) -> np.ndarray:
    """CpG-island-ish content: a CG-rich head over an AT-rich background,
    so the island caller has real work."""
    bg = rng.choice(4, size=n, p=[0.3, 0.2, 0.2, 0.3])
    k = max(1, n // 4)
    bg[:k] = rng.choice(4, size=k, p=[0.1, 0.4, 0.4, 0.1])
    return bg.astype(np.uint8)


def _write_fasta(path, records) -> str:
    bases = np.array(list("acgt"))
    with open(path, "w") as f:
        for name, syms in records:
            f.write(f">{name}\n")
            s = "".join(bases[syms])
            for i in range(0, len(s), 70):
                f.write(s[i : i + 70] + "\n")
    return str(path)


def _calls_by_name(calls) -> dict:
    out: dict = {}
    names = (
        calls.names if calls.names is not None
        else np.full(len(calls), ".", dtype=object)
    )
    for i in range(len(calls)):
        out.setdefault(str(names[i]), []).append((
            int(calls.beg[i]), int(calls.end[i]), int(calls.length[i]),
            float(calls.gc_content[i]), float(calls.oe_ratio[i]),
        ))
    return out


def _mixed_requests(rng, n=16):
    """>= 16 heterogeneous records: mixed lengths, decode + posterior,
    two tenants."""
    lengths = [350, 800, 1200, 2000, 3000, 4500, 6000, 9000]
    recs = []
    for i in range(n):
        kind = "decode" if i % 3 != 1 else "posterior"
        recs.append((
            f"rec{i}",
            _gen_symbols(rng, lengths[i % len(lengths)] + i),
            kind,
            f"t{i % 2}",
        ))
    return recs


# ---------------------------------------------------------------------------
# The acceptance test: broker == batch pipelines, warm and compile-stable.


@pytest.mark.slow
def test_broker_bit_identical_to_batch_pipelines(tmp_path):
    params = presets.durbin_cpg8()
    rng = np.random.default_rng(7)
    recs = _mixed_requests(rng, 16)
    assert len({t for *_, t in recs}) == 2
    decode_recs = [(nm, s) for nm, s, k, _ in recs if k == "decode"]
    post_recs = [(nm, s) for nm, s, k, _ in recs if k == "posterior"]
    assert len(decode_recs) >= 2 and len(post_recs) >= 2

    # Batch-pipeline ground truth on the same records.
    fa_d = _write_fasta(tmp_path / "d.fa", decode_recs)
    fa_p = _write_fasta(tmp_path / "p.fa", post_recs)
    dres = pipeline.decode_file(fa_d, params, compat=False)
    conf_path = str(tmp_path / "conf.npy")
    pres = pipeline.posterior_file(
        fa_p, params, confidence_out=conf_path,
        islands_out=str(tmp_path / "pi.txt"),
    )
    conf_all = np.load(conf_path)
    want_decode = _calls_by_name(dres.calls)
    want_post = _calls_by_name(pres.calls)
    post_conf = {}
    off = 0
    for nm, s in post_recs:
        post_conf[nm] = conf_all[off : off + s.size]
        off += s.size

    # The daemon's broker over the same records: small flush budget so the
    # stream coalesces into MULTIPLE mixed flushes (flat batches AND
    # single-record routes both exercised).
    sess = Session(params, name="test-serve", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=12_000, flush_deadline_s=0.0)
    )

    def submit_all(base: int) -> None:
        for i, (nm, s, k, ten) in enumerate(recs):
            broker.submit(
                request_id=base + i, tenant=ten, kind=k, symbols=s, name=nm
            )

    # Flush 1 of each geometry: compiles happen here.
    submit_all(0)
    warm = broker.drain()
    assert all(r.ok for r in warm)
    assert broker.flushes >= 2  # the stream really coalesced into flushes

    # Steady state: same geometries again — the obs ledger must show ZERO
    # fresh compiles and ZERO prepared-cache re-preps.
    from cpgisland_tpu.ops import prepared

    preps_before = prepared.cache_stats()["misses"]
    with obs.no_new_compiles("serve-steady-state"):
        submit_all(100)
        results = {r.id - 100: r for r in broker.drain()}
    assert prepared.cache_stats()["misses"] == preps_before
    assert len(results) == len(recs)

    # Bit-identical paths/calls/conf vs the batch pipelines.
    for i, (nm, s, kind, ten) in enumerate(recs):
        r = results[i]
        assert r.ok, r.error
        assert r.tenant == ten
        got = _calls_by_name(r.calls)
        want = (want_decode if kind == "decode" else want_post).get(nm, [])
        assert got.get(nm, []) == want, f"{kind} calls differ for {nm}"
        if kind == "posterior":
            assert r.conf is not None and np.array_equal(r.conf, post_conf[nm])

    # Multi-tenant accounting covered the whole stream.
    stats = broker.stats()
    assert set(stats["tenants"]) == {"t0", "t1"}
    total = sum(s.size for _, s, _, _ in recs)
    assert sum(t["symbols"] for t in stats["tenants"].values()) == 2 * total
    assert stats["flushed_symbols"] == 2 * total


# ---------------------------------------------------------------------------
# Flush policy


def test_flush_policy_budget_and_deadline():
    params = presets.durbin_cpg8()
    sess = Session(params, name="t", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=4096, flush_deadline_s=30.0)
    )
    rng = np.random.default_rng(0)
    broker.submit(
        request_id=0, tenant="a", kind="decode",
        symbols=_gen_symbols(rng, 1000),
    )
    # Under budget, deadline far away: not ready.
    assert not broker.flush_ready()
    broker.submit(
        request_id=1, tenant="a", kind="decode",
        symbols=_gen_symbols(rng, 4000),
    )
    # Budget reached: ready without waiting for the deadline.
    assert broker.flush_ready()
    results = broker.flush_once()
    assert [r.id for r in results] == [0, 1]
    assert broker.pending() == 0


def test_flush_deadline_fires_without_budget():
    params = presets.durbin_cpg8()
    sess = Session(params, name="t", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 30, flush_deadline_s=0.01)
    )
    rng = np.random.default_rng(0)
    broker.submit(
        request_id=0, tenant="a", kind="decode",
        symbols=_gen_symbols(rng, 600),
    )
    time.sleep(0.02)
    assert broker.flush_ready()  # deadline, not budget
    assert [r.id for r in broker.flush_once()] == [0]


def test_empty_flush_on_deadline_is_noop_and_loop_survives():
    """A deadline firing on an empty queue must not crash the worker loop,
    and the loop must still serve what arrives afterwards."""
    params = presets.durbin_cpg8()
    sess = Session(params, name="t", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 20, flush_deadline_s=0.005)
    )
    assert broker.flush_once() == []  # empty flush: no-op, not an error
    results = []
    got = threading.Event()

    def on_result(r):
        results.append(r)
        got.set()

    loop = ServeLoop(broker, on_result)
    loop.IDLE_WAIT_S = 0.01
    loop.start()
    time.sleep(0.05)  # several empty deadline wakeups
    broker.submit(
        request_id=0, tenant="a", kind="decode",
        symbols=_gen_symbols(np.random.default_rng(1), 900),
    )
    assert got.wait(timeout=120.0), "worker loop never delivered the result"
    loop.stop()
    assert results[0].ok and results[0].id == 0


# ---------------------------------------------------------------------------
# Admission control / oversized records


def test_tenant_cap_rejection_and_accounting():
    params = presets.durbin_cpg8()
    sess = Session(params, name="t", private_breaker=True)
    broker = RequestBroker(
        sess,
        BrokerConfig(
            flush_symbols=1 << 20, flush_deadline_s=10.0,
            tenant_max_requests=2,
        ),
    )
    rng = np.random.default_rng(0)
    for i in range(2):
        broker.submit(
            request_id=i, tenant="greedy", kind="decode",
            symbols=_gen_symbols(rng, 500),
        )
    with pytest.raises(Backpressure) as ei:
        broker.submit(
            request_id=2, tenant="greedy", kind="decode",
            symbols=_gen_symbols(rng, 500),
        )
    assert ei.value.reason == "tenant_requests"
    # Another tenant is NOT blocked by the greedy one's cap.
    broker.submit(
        request_id=3, tenant="polite", kind="decode",
        symbols=_gen_symbols(rng, 500),
    )
    stats = broker.stats()["tenants"]
    assert stats["greedy"]["rejected"] == 1
    assert stats["polite"]["rejected"] == 0
    results = broker.drain()
    assert sorted(r.id for r in results) == [0, 1, 3]


def test_tenant_symbol_cap():
    params = presets.durbin_cpg8()
    sess = Session(params, name="t", private_breaker=True)
    broker = RequestBroker(
        sess,
        BrokerConfig(
            flush_symbols=1 << 20, flush_deadline_s=10.0,
            tenant_max_symbols=1500,
        ),
    )
    rng = np.random.default_rng(0)
    broker.submit(
        request_id=0, tenant="a", kind="decode",
        symbols=_gen_symbols(rng, 1000),
    )
    with pytest.raises(Backpressure) as ei:
        broker.submit(
            request_id=1, tenant="a", kind="decode",
            symbols=_gen_symbols(rng, 1000),
        )
    assert ei.value.reason == "tenant_symbols"
    broker.drain()


@pytest.mark.slow
def test_oversized_record_routes_to_span_path_without_starving(tmp_path):
    """A single record exceeding the flush budget is admitted, routes to
    the span-threaded record path, and does NOT starve later requests."""
    params = presets.durbin_cpg8()
    rng = np.random.default_rng(3)
    big = _gen_symbols(rng, 20_000)
    small = _gen_symbols(rng, 700)
    sess = Session(params, name="t", private_breaker=True)
    broker = RequestBroker(
        sess,
        BrokerConfig(
            flush_symbols=4096, flush_deadline_s=0.0, decode_span=8192
        ),
    )
    broker.submit(request_id=0, tenant="a", kind="decode", symbols=big,
                  name="big")
    broker.submit(request_id=1, tenant="b", kind="decode", symbols=small,
                  name="small")
    results = {r.id: r for r in broker.drain()}
    assert results[0].ok and results[0].route == "span"
    assert results[1].ok  # the queue kept moving behind the oversized record
    # Span-threaded serving result == the batch pipeline's one-shot decode.
    fa = _write_fasta(tmp_path / "big.fa", [("big", big)])
    want = _calls_by_name(pipeline.decode_file(fa, params, compat=False).calls)
    got = _calls_by_name(results[0].calls)
    assert got.get("big", []) == want.get("big", want.get(".", []))


def test_posterior_over_span_rejected_at_admission():
    params = presets.durbin_cpg8()
    sess = Session(params, name="t", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(posterior_span=4096)
    )
    with pytest.raises(ValueError, match="posterior span"):
        broker.submit(
            request_id=0, tenant="a", kind="posterior",
            symbols=np.zeros(8192, np.uint8),
        )


# ---------------------------------------------------------------------------
# Per-session resilience


def test_breaker_trip_mid_flush_redispatches_and_stays_per_session(monkeypatch):
    """A fault inside a flush's supervised unit re-dispatches (the request
    still succeeds), feeds the SESSION's breaker — and the process-global
    breaker stays untouched."""
    params = presets.durbin_cpg8()
    sess = Session(
        params, name="t", retry_policy=FAST,
        breaker=resilience.EngineBreaker(threshold=1, cooldown_s=60.0),
    )
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 14, flush_deadline_s=0.0)
    )
    orig_run = sess.supervisor.run
    state = {"faults": 1}

    def run(thunk, **kw):
        def flaky():
            if state["faults"] > 0:
                state["faults"] -= 1
                raise RuntimeError("injected transient fault")
            return thunk()

        return orig_run(flaky, **kw)

    monkeypatch.setattr(sess.supervisor, "run", run)
    broker.submit(
        request_id=0, tenant="a", kind="decode",
        symbols=_gen_symbols(np.random.default_rng(5), 1200), name="r0",
    )
    results = broker.drain()
    assert results[0].ok  # the supervised unit re-dispatched mid-flush
    assert sess.supervisor.retries >= 1
    # threshold=1: the injected fault tripped the SESSION breaker...
    assert sess.breaker.tripped("decode.xla")
    # ...while the process-global breaker never saw it.
    assert not resilience.get_breaker().tripped("decode.xla")


def test_session_rejects_conflicting_call_config(tmp_path):
    params = presets.durbin_cpg8()
    fa = _write_fasta(
        tmp_path / "a.fa",
        [("r0", _gen_symbols(np.random.default_rng(0), 800))],
    )
    sess = Session(params, name="t", private_breaker=True)
    with pytest.raises(ValueError, match="session"):
        pipeline.decode_file(fa, params, compat=False, session=sess,
                             engine="xla")
    with pytest.raises(ValueError, match="Session"):
        pipeline.decode_file(
            fa, presets.two_state_cpg(), compat=False, session=sess,
            island_states=(0,),
        )


@pytest.mark.slow
def test_pipeline_drives_explicit_session(tmp_path):
    """decode_file/posterior_file with an explicit Session produce the
    same output as without (the session layer cannot diverge), and reuse
    the session's supervisor."""
    params = presets.durbin_cpg8()
    rng = np.random.default_rng(9)
    recs = [(f"r{i}", _gen_symbols(rng, 700 + 500 * i)) for i in range(3)]
    fa = _write_fasta(tmp_path / "a.fa", recs)
    sess = Session(params, name="t", private_breaker=True)
    r_sess = pipeline.decode_file(fa, params, compat=False, session=sess)
    r_none = pipeline.decode_file(fa, params, compat=False)
    assert _calls_by_name(r_sess.calls) == _calls_by_name(r_none.calls)
    p_sess = pipeline.posterior_file(
        fa, params, islands_out=str(tmp_path / "i1.txt"), session=sess
    )
    p_none = pipeline.posterior_file(
        fa, params, islands_out=str(tmp_path / "i2.txt")
    )
    assert p_sess.mean_island_confidence == p_none.mean_island_confidence
    assert _calls_by_name(p_sess.calls) == _calls_by_name(p_none.calls)


# ---------------------------------------------------------------------------
# Daemon restart: resume from PR 5 manifests


@pytest.mark.slow
def test_restarted_daemon_replays_from_manifest(tmp_path):
    params = presets.durbin_cpg8()
    rng = np.random.default_rng(13)
    recs = [
        (i, f"rec{i}", "decode" if i % 2 == 0 else "posterior",
         _gen_symbols(rng, 900 + 400 * i))
        for i in range(4)
    ]
    mpath = str(tmp_path / "serve.manifest.jsonl")
    cfg = BrokerConfig(flush_symbols=1 << 14, flush_deadline_s=0.0)

    sess1 = Session(params, name="t1", private_breaker=True)
    b1 = RequestBroker(sess1, cfg, manifest_path=mpath, resume=False)
    for rid, nm, kind, syms in recs:
        b1.submit(request_id=rid, tenant="a", kind=kind, symbols=syms,
                  name=nm)
    first = {r.id: r for r in b1.drain()}
    assert all(r.ok for r in first.values())
    b1.close()  # the "kill": the daemon goes away, the manifest survives

    sess2 = Session(params, name="t2", private_breaker=True)
    b2 = RequestBroker(sess2, cfg, manifest_path=mpath, resume=True)
    for rid, nm, kind, syms in recs:
        b2.submit(request_id=rid, tenant="a", kind=kind, symbols=syms,
                  name=nm)
    second = {r.id: r for r in b2.drain()}
    assert b2.flushes == 0  # every request replayed, zero device work
    for rid, nm, kind, syms in recs:
        r1, r2 = first[rid], second[rid]
        assert r2.replayed and r2.route == "replay"
        assert _calls_by_name(r2.calls) == _calls_by_name(r1.calls)
        # gc/oe floats round-trip bit-exactly through the manifest wire.
        assert np.array_equal(r2.calls.gc_content, r1.calls.gc_content)
        assert np.array_equal(r2.calls.oe_ratio, r1.calls.oe_ratio)
        if kind == "posterior":
            assert r2.conf_sum == r1.conf_sum
    b2.close()


def test_duplicate_queued_id_rejected_and_reusable_after_completion():
    """Without a manifest, two same-id requests in flight would collide in
    the per-flush results map — rejected at admission; the id is free
    again once its request completed."""
    params = presets.durbin_cpg8()
    sess = Session(params, name="t", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 14, flush_deadline_s=0.0)
    )
    rng = np.random.default_rng(0)
    broker.submit(request_id=5, tenant="a", kind="decode",
                  symbols=_gen_symbols(rng, 500))
    with pytest.raises(ValueError, match="already queued"):
        broker.submit(request_id=5, tenant="b", kind="decode",
                      symbols=_gen_symbols(rng, 500))
    assert [r.id for r in broker.drain()] == [5]
    # Completed: the id may be reused.
    broker.submit(request_id=5, tenant="a", kind="decode",
                  symbols=_gen_symbols(rng, 500))
    assert [r.id for r in broker.drain()] == [5]


def test_failed_request_id_retryable_in_manifest_mode(tmp_path, monkeypatch):
    """A request whose unit gave up (ok=False) recorded nothing in the
    manifest — its id must be free for a same-id retry (the manifest keys
    replay by id, so a fresh id would break restart identity)."""
    params = presets.durbin_cpg8()
    sess = Session(params, name="t", retry_policy=FAST, private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 14, flush_deadline_s=0.0),
        manifest_path=str(tmp_path / "m.jsonl"),
    )
    orig_run = sess.supervisor.run
    state = {"fail": True}

    def run(thunk, **kw):
        if state["fail"]:
            raise RuntimeError("persistent injected fault")
        return orig_run(thunk, **kw)

    monkeypatch.setattr(sess.supervisor, "run", run)
    syms = _gen_symbols(np.random.default_rng(2), 700)
    broker.submit(request_id=3, tenant="a", kind="decode", symbols=syms,
                  name="r3")
    (failed,) = broker.drain()
    assert not failed.ok
    # Same-id retry after the fault clears: admitted and served.
    state["fail"] = False
    broker.submit(request_id=3, tenant="a", kind="decode", symbols=syms,
                  name="r3")
    (ok,) = broker.drain()
    assert ok.ok and ok.id == 3 and not ok.replayed
    broker.close()


def test_manifest_mode_rejects_duplicate_ids(tmp_path):
    params = presets.durbin_cpg8()
    sess = Session(params, name="t", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 14, flush_deadline_s=0.0),
        manifest_path=str(tmp_path / "m.jsonl"),
    )
    syms = _gen_symbols(np.random.default_rng(0), 600)
    broker.submit(request_id=7, tenant="a", kind="decode", symbols=syms)
    with pytest.raises(ValueError, match="duplicate request id"):
        broker.submit(request_id=7, tenant="a", kind="decode", symbols=syms)
    broker.drain()
    broker.close()


# ---------------------------------------------------------------------------
# Prepared-cache lifecycle (satellite: serve daemons dropping tenants)


def test_prepared_cache_lifecycle_counters_and_explicit_eviction():
    import jax.numpy as jnp

    from cpgisland_tpu.ops import prepared

    prepared.clear_cache()
    streams = prepared.PreparedStreams(4)
    arr = jnp.asarray(
        np.random.default_rng(0).integers(0, 4, size=4096).astype(np.uint8)
    )
    p1 = streams.seq(arr, 4096, lane_T=512, t_tile=256)
    st = prepared.cache_stats()
    assert st["entries"] == 1 and st["misses"] == 1
    assert st["resident_bytes"] > 0
    p2 = streams.seq(arr, 4096, lane_T=512, t_tile=256)
    assert p2 is p1
    assert prepared.cache_stats()["hits"] == 1
    # The daemon's drop-a-tenant hook: explicit eviction, counted.
    assert streams.clear_session() == 1
    st = prepared.cache_stats()
    assert st["entries"] == 0 and st["resident_bytes"] == 0
    assert st["evictions_explicit"] == 1
    # Re-prep after eviction is a fresh miss (no stale aliasing).
    p3 = streams.seq(arr, 4096, lane_T=512, t_tile=256)
    assert p3 is not p1
    assert prepared.cache_stats()["misses"] == 2
    streams.clear_session()
    prepared.clear_cache()


def test_cache_stats_surface_in_obs_summary():
    with obs.observe(metrics=None) as ob:
        pass
    summary = ob.summary()
    assert "prepared_cache" in summary
    assert {"hits", "misses", "entries", "resident_bytes"} <= set(
        summary["prepared_cache"]
    )


# ---------------------------------------------------------------------------
# Transport


def _seq_text(syms: np.ndarray) -> str:
    return "".join("acgt"[s] for s in syms)


@pytest.mark.slow
def test_transport_jsonl_stream_roundtrip():
    from cpgisland_tpu.serve import transport

    params = presets.durbin_cpg8()
    rng = np.random.default_rng(21)
    d_syms = _gen_symbols(rng, 1100)
    p_syms = _gen_symbols(rng, 900)
    lines = [
        json.dumps({"id": 0, "kind": "decode", "tenant": "t0",
                    "name": "chrA", "seq": _seq_text(d_syms)}),
        json.dumps({"id": 1, "kind": "posterior", "tenant": "t1",
                    "name": "chrB", "seq": _seq_text(p_syms),
                    "want_conf": True}),
        json.dumps({"id": 2, "kind": "bogus", "seq": "acgt"}),
        "this is not json",
        json.dumps({"op": "stats"}),
        json.dumps({"op": "shutdown"}),
    ]
    inp = io.StringIO("\n".join(lines) + "\n")
    out = io.StringIO()
    sess = Session(params, name="t", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 15, flush_deadline_s=0.0)
    )
    served = transport.serve_stream(inp, out, broker, use_worker=False)
    assert served == 2
    responses = [json.loads(ln) for ln in out.getvalue().splitlines()]
    by_id = {r.get("id"): r for r in responses if r.get("ok") and "stats" not in r}
    errors = [r for r in responses if not r.get("ok")]
    stats = [r for r in responses if "stats" in r]
    assert len(errors) == 2  # bogus kind + bad JSON line
    assert len(stats) == 1 and stats[0]["stats"]["flushes"] >= 0
    dec = by_id[0]
    assert dec["kind"] == "decode" and "islands" in dec
    # The wire form reconstructs calls bit-identically.
    from cpgisland_tpu.resilience.manifest import calls_from_wire

    calls = calls_from_wire(dec["islands"])
    assert dec["islands_text"] == calls.format_lines()
    post = by_id[1]
    assert post["kind"] == "posterior"
    assert len(post["conf"]) == p_syms.size
    np.testing.assert_allclose(
        sum(post["conf"]), float.fromhex(post["conf_sum"]), rtol=1e-5
    )
    assert broker.closed  # shutdown op closed admission


def test_explicit_session_engine_reaches_dispatch(tmp_path, monkeypatch):
    """An explicit session's engine request reaches the span/record
    dispatches, not just the batch lowering: check_call forces the call
    kwarg to its 'auto' default, so the pipeline must source the engine
    from the session everywhere (a mismatch would mix lowerings in one
    call and mislabel the obs/retry telemetry)."""
    params = presets.durbin_cpg8()
    fa = _write_fasta(
        tmp_path / "a.fa",
        [("r0", _gen_symbols(np.random.default_rng(3), 900))],
    )

    seen: list = []
    real_vs = pipeline.viterbi_sharded

    def rec_vs(*a, engine="auto", **k):
        seen.append(engine)
        return real_vs(*a, engine=engine, **k)

    monkeypatch.setattr(pipeline, "viterbi_sharded", rec_vs)
    sess = Session(params, engine="xla", name="t", private_breaker=True)
    pipeline.decode_file(fa, params, compat=False, session=sess)
    assert seen and all(e == "xla" for e in seen)

    from cpgisland_tpu.parallel import posterior as post_mod

    seen2: list = []
    real_ps = post_mod.posterior_sharded

    def rec_ps(*a, engine="auto", **k):
        seen2.append(engine)
        return real_ps(*a, engine=engine, **k)

    monkeypatch.setattr(post_mod, "posterior_sharded", rec_ps)
    sess2 = Session(params, engine="xla", name="t2", private_breaker=True)
    pipeline.posterior_file(
        fa, params, islands_out=str(tmp_path / "i.txt"), session=sess2
    )
    assert seen2 and all(e == "xla" for e in seen2)


class _DyingStream:
    """A line stream that dies (connection reset) after its lines."""

    def __init__(self, lines):
        self._it = iter(lines)

    def __iter__(self):
        return self

    def __next__(self):
        for line in self._it:
            return line
        raise OSError("connection reset by peer")


@pytest.mark.slow
def test_dead_stream_drains_broker_no_cross_connection_leak():
    """A connection dying mid-stream must not leave its admitted requests
    queued in the shared broker: socket mode reuses ONE broker across
    connections, and a skipped drain would flush the dead client's
    requests into the NEXT client's stream."""
    from cpgisland_tpu.serve import transport

    params = presets.durbin_cpg8()
    syms = _gen_symbols(np.random.default_rng(5), 1000)
    sess = Session(params, name="t", private_breaker=True)
    # Big budget + long deadline: the request stays queued when the
    # stream dies, so only the finally-drain can serve it.
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 22, flush_deadline_s=60.0)
    )
    out1 = io.StringIO()
    with pytest.raises(OSError):
        transport.serve_stream(
            _DyingStream([json.dumps(
                {"id": 0, "kind": "decode", "seq": _seq_text(syms)}
            ) + "\n"]),
            out1, broker, use_worker=False,
        )
    assert broker.pending() == 0  # drained despite the dead connection
    r1 = [json.loads(ln) for ln in out1.getvalue().splitlines()]
    assert [r["id"] for r in r1 if r.get("ok")] == [0]
    # "Next client": a fresh stream sees none of the dead client's results.
    out2 = io.StringIO()
    transport.serve_stream(
        io.StringIO(json.dumps({"op": "shutdown"}) + "\n"),
        out2, broker, use_worker=False,
    )
    assert out2.getvalue() == ""


@pytest.mark.slow
def test_rejected_duplicate_keeps_want_conf_flag():
    """A rejected duplicate id must not clobber the want_conf flag an
    earlier still-queued request set."""
    from cpgisland_tpu.serve import transport

    params = presets.durbin_cpg8()
    syms = _gen_symbols(np.random.default_rng(6), 800)
    sess = Session(params, name="t", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 22, flush_deadline_s=60.0)
    )
    lines = [
        json.dumps({"id": 7, "kind": "posterior",
                    "seq": _seq_text(syms), "want_conf": True}),
        # Duplicate id while the first is queued: rejected by the broker,
        # and its (absent) want_conf must not erase the first's flag.
        json.dumps({"id": 7, "kind": "posterior", "seq": _seq_text(syms)}),
    ]
    out = io.StringIO()
    transport.serve_stream(
        io.StringIO("\n".join(lines) + "\n"), out, broker, use_worker=False
    )
    responses = [json.loads(ln) for ln in out.getvalue().splitlines()]
    ok = [r for r in responses if r.get("ok")]
    errors = [r for r in responses if not r.get("ok")]
    assert len(ok) == 1 and len(errors) == 1
    assert "already queued" in errors[0]["error"]
    assert len(ok[0]["conf"]) == syms.size  # the flag survived


def test_broker_record_paths_dispatch_raw_session_engine(monkeypatch):
    """The broker's per-record decode/posterior units must dispatch the
    RAW session engine string (like decode_file/posterior_file), not the
    flush-resolved name — an explicit resolved name is honored as-is, so
    supervisor retries after a breaker trip could never demote down the
    session's parity-twin ladder."""
    from cpgisland_tpu.parallel import decode as par_decode
    from cpgisland_tpu.parallel import posterior as post_mod

    params = presets.durbin_cpg8()
    rng = np.random.default_rng(17)

    seen_d: list = []
    real_vs = par_decode.viterbi_sharded

    def rec_vs(*a, engine="auto", **k):
        seen_d.append(engine)
        return real_vs(*a, engine=engine, **k)

    seen_p: list = []
    real_ps = post_mod.posterior_sharded

    def rec_ps(*a, engine="auto", **k):
        seen_p.append(engine)
        return real_ps(*a, engine=engine, **k)

    monkeypatch.setattr(par_decode, "viterbi_sharded", rec_vs)
    monkeypatch.setattr(post_mod, "posterior_sharded", rec_ps)
    sess = Session(params, name="t", private_breaker=True)  # engine='auto'
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 14, flush_deadline_s=0.0)
    )
    # A single decode request takes the record path (flush_small rule);
    # the posterior request takes the shared record unit.
    broker.submit(request_id=0, tenant="a", kind="decode",
                  symbols=_gen_symbols(rng, 700), name="d")
    broker.submit(request_id=1, tenant="a", kind="posterior",
                  symbols=_gen_symbols(rng, 600), name="p")
    assert all(r.ok for r in broker.drain())
    assert seen_d and all(e == "auto" for e in seen_d)
    assert seen_p and all(e == "auto" for e in seen_p)


def test_duplicate_id_rejected_while_executing(monkeypatch):
    """submit must reject a duplicate id while the first request is
    EXECUTING in a flush (not just while queued), and free the id once
    its result is returned."""
    params = presets.durbin_cpg8()
    rng = np.random.default_rng(19)
    syms = _gen_symbols(rng, 600)
    sess = Session(params, name="t", private_breaker=True)
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=1 << 14, flush_deadline_s=0.0)
    )
    real_run = broker._run_flush

    def run_and_probe(batch, t_taken):
        # Mid-flush: the id left the queue but its result isn't back yet.
        with pytest.raises(ValueError, match="already queued"):
            broker.submit(request_id=batch[0].id, tenant="a",
                          kind="decode", symbols=syms, name="dup")
        return real_run(batch, t_taken)

    monkeypatch.setattr(broker, "_run_flush", run_and_probe)
    broker.submit(request_id=1, tenant="a", kind="decode", symbols=syms,
                  name="r1")
    assert [r.ok for r in broker.drain()] == [True]
    # Completed: the id is reusable.
    broker.submit(request_id=1, tenant="a", kind="decode", symbols=syms,
                  name="r1b")
    assert all(r.ok for r in broker.drain())


def test_clear_session_sweeps_dead_keyed_entries():
    """A dropped tenant's arrays usually die BEFORE Session.close() runs
    its clear_session hook — the hook must release the dead-keyed prep
    trees then, not at the next unrelated cache miss."""
    import gc

    import jax.numpy as jnp

    from cpgisland_tpu.ops import prepared

    prepared.clear_cache()
    streams = prepared.PreparedStreams(4)
    arr = jnp.asarray(
        np.random.default_rng(1).integers(0, 4, size=4096).astype(np.uint8)
    )
    streams.seq(arr, 4096, lane_T=512, t_tile=256)
    assert prepared.cache_stats()["entries"] == 1
    del arr
    gc.collect()
    streams.clear_session()
    st = prepared.cache_stats()
    assert st["entries"] == 0 and st["resident_bytes"] == 0
    assert st["evictions_dead"] >= 1
    prepared.clear_cache()
