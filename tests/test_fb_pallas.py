"""Pallas forward-backward E-step vs. the XLA rescaled path and the oracle."""

import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops.fb_pallas import batch_stats_pallas
from cpgisland_tpu.ops.forward_backward import batch_stats
from cpgisland_tpu.train import baum_welch, backends
from cpgisland_tpu.utils import chunking


def _random_model(rng, k=8, m=4):
    return HmmParams.from_probs(
        rng.dirichlet(np.ones(k)),
        rng.dirichlet(np.ones(k), size=k),
        rng.dirichlet(np.ones(m), size=k),
    )


def _assert_stats_close(a, b, atol=2e-3):
    np.testing.assert_allclose(np.asarray(a.init), np.asarray(b.init), atol=atol)
    np.testing.assert_allclose(np.asarray(a.trans), np.asarray(b.trans), atol=atol * np.asarray(b.trans).max())
    np.testing.assert_allclose(np.asarray(a.emit), np.asarray(b.emit), atol=atol * np.asarray(b.emit).max())
    np.testing.assert_allclose(float(a.loglik), float(b.loglik), rtol=1e-4)
    assert int(a.n_seqs) == int(b.n_seqs)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_matches_xla_rescaled_full_chunks(rng):
    params = _random_model(rng)
    chunks = jnp.asarray(rng.integers(0, 4, size=(5, 256)))
    lengths = jnp.full(5, 256, jnp.int32)
    a = batch_stats_pallas(params, chunks, lengths, t_tile=64)
    b = batch_stats(params, chunks, lengths, mode="rescaled")
    _assert_stats_close(a, b)


def test_matches_xla_padded_and_empty(rng):
    params = _random_model(rng)
    chunks = jnp.asarray(rng.integers(0, 4, size=(4, 200)))
    lengths = jnp.asarray([200, 130, 1, 0], jnp.int32)
    a = batch_stats_pallas(params, chunks, lengths, t_tile=64)
    b = batch_stats(params, chunks, lengths, mode="rescaled")
    _assert_stats_close(a, b)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_durbin_preset_structural_zeros(rng):
    params = presets.durbin_cpg8()
    chunks = jnp.asarray(rng.integers(0, 4, size=(3, 192)))
    lengths = jnp.full(3, 192, jnp.int32)
    a = batch_stats_pallas(params, chunks, lengths, t_tile=64)
    B0 = np.asarray(params.B)
    assert (np.asarray(a.emit)[B0 == 0] == 0).all()
    b = batch_stats(params, chunks, lengths, mode="rescaled")
    _assert_stats_close(a, b)


def test_uneven_t_tiling(rng):
    params = _random_model(rng)
    chunks = jnp.asarray(rng.integers(0, 4, size=(2, 250)))  # not a tile multiple
    lengths = jnp.asarray([250, 250], jnp.int32)
    a = batch_stats_pallas(params, chunks, lengths, t_tile=64)
    b = batch_stats(params, chunks, lengths, mode="rescaled")
    _assert_stats_close(a, b)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_local_backend_pallas_engine_trains(rng):
    syms = rng.integers(0, 4, size=2048).astype(np.uint8)
    ck = chunking.frame(syms, 256)
    res_x = baum_welch.fit(
        presets.durbin_cpg8(), ck, num_iters=2, convergence=0.0,
        backend=backends.LocalBackend(engine="xla"),
    )
    res_p = baum_welch.fit(
        presets.durbin_cpg8(), ck, num_iters=2, convergence=0.0,
        backend=backends.LocalBackend(engine="pallas"),
    )
    np.testing.assert_allclose(
        np.asarray(res_p.params.A), np.asarray(res_x.params.A), atol=1e-3
    )


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_spmd_backend_pallas_engine(rng):
    params = _random_model(rng)
    chunks = rng.integers(0, 4, size=(16, 128)).astype(np.uint8)
    ck = chunking.Chunked(
        chunks=chunks, lengths=np.full(16, 128, np.int64), total=16 * 128
    )
    spmd_p = backends.SpmdBackend(engine="pallas")
    spmd_x = backends.SpmdBackend(engine="xla")
    cp, lp = spmd_p.place(ck.chunks, ck.lengths)
    a = spmd_p(params, cp, lp)
    b = spmd_x(params, cp, lp)
    _assert_stats_close(a, b)


def test_engine_validation():
    params = presets.durbin_cpg8()
    with pytest.raises(ValueError, match="rescaled"):
        backends.resolve_fb_engine("pallas", params, "log")
    with pytest.raises(ValueError, match="unknown engine"):
        backends.resolve_fb_engine("bogus", params, "rescaled")


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_t_not_multiple_of_row_tile(rng):
    """T below the t-tile and not a multiple of 8: the row-tiled forward must
    cover every position (a truncating tile loop once dropped T % 8 rows)."""
    params = presets.durbin_cpg8()
    for T in (250, 7, 63):
        chunks = jnp.asarray(rng.integers(0, 4, size=(4, T), dtype=np.int32).astype(np.uint8))
        lengths = jnp.asarray(rng.integers(1, T + 1, size=4), dtype=jnp.int32)
        got = batch_stats_pallas(params, chunks, lengths)
        want = batch_stats(params, chunks, lengths, mode="rescaled")
        np.testing.assert_allclose(np.asarray(got.trans), np.asarray(want.trans), rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(got.emit), np.asarray(want.emit), rtol=2e-4, atol=2e-4)
        assert float(got.loglik) == pytest.approx(float(want.loglik), abs=0.01)


# ---------------------------------------------------------------------------
# Whole-sequence fused-kernel path (seq_stats_pallas)


def _oracle_seq_stats(pi, A, B, obs):
    import oracle

    K, M = B.shape
    gamma, xi_sum, ll = oracle.forward_backward_oracle(pi, A, B, obs)
    emit = np.zeros((K, M))
    np.add.at(emit.T, obs, gamma)
    return gamma[0], xi_sum, emit, ll


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_seq_stats_pallas_matches_oracle(rng):
    """Exact whole-sequence stats with lane-boundary messages == float64
    oracle on the UNDIVIDED sequence (pairs crossing every lane counted)."""
    from cpgisland_tpu.ops.fb_pallas import seq_stats_pallas

    pi = rng.dirichlet(np.ones(3))
    A = rng.dirichlet(np.ones(3), size=3)
    B = rng.dirichlet(np.ones(4), size=3)
    params = HmmParams.from_probs(pi, A, B)
    for T in (3203, 257, 64):  # ragged vs the 256-symbol test lanes
        obs = rng.integers(0, 4, size=T).astype(np.uint8)
        g0, xi, emit, ll = _oracle_seq_stats(pi, A, B, obs)
        st = seq_stats_pallas(params, jnp.asarray(obs), T, lane_T=256, t_tile=64)
        np.testing.assert_allclose(np.asarray(st.init), g0, atol=5e-5)  # TPU exp/log
        np.testing.assert_allclose(np.asarray(st.trans), xi, rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(st.emit), emit, rtol=2e-4, atol=2e-4)
        # loglik error grows with T on TPU (~2e-5-relative exp/log per term)
        assert float(st.loglik) == pytest.approx(ll, abs=max(0.02, 5e-5 * T))
        assert int(st.n_seqs) == 1


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_seq_stats_pallas_durbin_em_step(rng):
    """One EM step through the fused whole-sequence path == chunk-free oracle."""
    import oracle

    from cpgisland_tpu.ops.fb_pallas import seq_stats_pallas
    from cpgisland_tpu.train.baum_welch import mstep

    params = presets.durbin_cpg8()
    obs = rng.integers(0, 4, size=5000).astype(np.uint8)
    pi_o, A_o, B_o, _ = oracle.em_step_oracle(
        np.asarray(params.pi, np.float64),
        np.asarray(params.A, np.float64),
        np.asarray(params.B, np.float64),
        [obs],
    )
    st = seq_stats_pallas(params, jnp.asarray(obs), 5000, lane_T=512, t_tile=64)
    got = mstep(params, st)
    np.testing.assert_allclose(np.asarray(got.pi), pi_o, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got.A), A_o, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(got.B), B_o, rtol=1e-4, atol=1e-4)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_seq_stats_pallas_padded_and_empty(rng):
    from cpgisland_tpu.ops.fb_pallas import seq_stats_pallas

    pi = rng.dirichlet(np.ones(2))
    A = rng.dirichlet(np.ones(2), size=2)
    B = rng.dirichlet(np.ones(4), size=2)
    params = HmmParams.from_probs(pi, A, B)
    obs = rng.integers(0, 4, size=1000).astype(np.uint8)
    # length < buffer: the tail must contribute nothing
    g0, xi, emit, ll = _oracle_seq_stats(pi, A, B, obs[:700])
    st = seq_stats_pallas(params, jnp.asarray(obs), 700, lane_T=256, t_tile=64)
    np.testing.assert_allclose(np.asarray(st.trans), xi, rtol=2e-4, atol=2e-4)
    assert float(st.loglik) == pytest.approx(ll, abs=0.05)
    # empty
    st0 = seq_stats_pallas(params, jnp.asarray(obs), 0, lane_T=256, t_tile=64)
    assert float(st0.loglik) == 0.0
    assert int(st0.n_seqs) == 0
    np.testing.assert_array_equal(np.asarray(st0.trans), 0.0)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_seq_stats_pallas_slow_mixing_boundary_exactness(rng):
    """Adversarial slow-mixing model: lane-boundary messages must be EXACT —
    an off-by-one in the lane-0 transfer product once cost 0.08 absolute
    transition error here (vs ~1e-5 float noise)."""
    import oracle

    from cpgisland_tpu.ops.fb_pallas import seq_stats_pallas

    pi = np.array([0.99, 0.01])
    A = np.array([[0.9, 0.1], [0.1, 0.9]])
    B = np.array([[0.26, 0.24, 0.25, 0.25], [0.24, 0.26, 0.25, 0.25]])
    params = HmmParams.from_probs(pi, A, B)
    obs = rng.integers(0, 4, size=64).astype(np.uint8)
    _, xi, ll = oracle.forward_backward_oracle(pi, A, B, obs)
    st = seq_stats_pallas(params, jnp.asarray(obs), 64, lane_T=8, t_tile=8)
    # 5e-4: TPU exp/log noise on counts of magnitude ~30; the bug this
    # guards against was 0.08
    np.testing.assert_allclose(np.asarray(st.trans), xi, atol=5e-4)
    assert float(st.loglik) == pytest.approx(ll, abs=1e-3)


def test_seq_stats_pallas_rejects_misaligned_lane_T():
    from cpgisland_tpu.ops.fb_pallas import seq_stats_pallas

    params = presets.durbin_cpg8()
    obs = jnp.zeros(960, jnp.uint8)
    with pytest.raises(ValueError, match="multiple"):
        seq_stats_pallas(params, obs, 960, lane_T=96, t_tile=64)
    with pytest.raises(ValueError, match="multiple"):
        seq_stats_pallas(params, obs, 960, lane_T=100, t_tile=64)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_seq_stats_pallas_sharded_mesh_matches_oracle(rng):
    """The fused whole-sequence E-step across an 8-device mesh: per-device
    lane products + gathered boundary messages == float64 oracle on the
    undivided sequence (kernels run interpreted on the virtual CPU mesh)."""
    import jax

    from conftest import require_devices

    from cpgisland_tpu.parallel.fb_sharded import (
        shard_sequence,
        sharded_stats_pallas_fn,
    )
    from cpgisland_tpu.parallel.mesh import make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    require_devices(8)
    pi = rng.dirichlet(np.ones(3))
    A = rng.dirichlet(np.ones(3), size=3)
    B = rng.dirichlet(np.ones(4), size=3)
    params = HmmParams.from_probs(pi, A, B)
    T = 5003
    obs = rng.integers(0, 4, size=T).astype(np.uint8)
    g0, xi, emit, ll = _oracle_seq_stats(pi, A, B, obs)

    mesh = make_mesh(8, axis="seq")
    obs_p, lengths = shard_sequence(obs, 8, block_size=256, pad_value=4)
    arr = jax.device_put(jnp.asarray(obs_p), NamedSharding(mesh, P("seq")))
    lens = jax.device_put(jnp.asarray(lengths), NamedSharding(mesh, P("seq")))
    st = sharded_stats_pallas_fn(mesh, 64, 64)(params, arr, lens)
    np.testing.assert_allclose(np.asarray(st.init), g0, atol=5e-5)
    np.testing.assert_allclose(np.asarray(st.trans), xi, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(st.emit), emit, rtol=2e-4, atol=2e-4)
    assert float(st.loglik) == pytest.approx(ll, abs=max(0.02, 5e-5 * T))
    assert int(st.n_seqs) == 1


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_seq_stats_pallas_sharded_sticky_boundaries(rng):
    """Device AND lane boundary messages on the adversarial slow-mixing
    model — the cross-shard pairs must be exact."""
    import jax
    import oracle

    from conftest import require_devices

    from cpgisland_tpu.parallel.fb_sharded import (
        shard_sequence,
        sharded_stats_pallas_fn,
    )
    from cpgisland_tpu.parallel.mesh import make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    require_devices(8)
    pi = np.array([0.99, 0.01])
    A = np.array([[0.9, 0.1], [0.1, 0.9]])
    B = np.array([[0.26, 0.24, 0.25, 0.25], [0.24, 0.26, 0.25, 0.25]])
    params = HmmParams.from_probs(pi, A, B)
    obs = rng.integers(0, 4, size=512).astype(np.uint8)
    _, xi, ll = oracle.forward_backward_oracle(pi, A, B, obs)

    mesh = make_mesh(8, axis="seq")
    obs_p, lengths = shard_sequence(obs, 8, block_size=64, pad_value=4)
    arr = jax.device_put(jnp.asarray(obs_p), NamedSharding(mesh, P("seq")))
    lens = jax.device_put(jnp.asarray(lengths), NamedSharding(mesh, P("seq")))
    st = sharded_stats_pallas_fn(mesh, 16, 16)(params, arr, lens)
    np.testing.assert_allclose(np.asarray(st.trans), xi, atol=5e-4)
    assert float(st.loglik) == pytest.approx(ll, abs=0.01)


def test_pick_lane_t_cost_model():
    """Lane selection minimizes padded-grid work over measured rates: long
    lanes win once they fill the 128-lane grid, but an input just past a
    grid boundary must NOT pay a half-empty long-lane grid (r4 review
    finding: a raw size gate made those ~20% slower than the default)."""
    from cpgisland_tpu.ops.fb_pallas import (
        DEFAULT_LANE_T,
        LANE_TILE,
        _LANE_RATE,
        pick_lane_T,
    )

    assert pick_lane_T(1) == DEFAULT_LANE_T
    assert pick_lane_T(1 << 20) == DEFAULT_LANE_T
    # exactly full grids pick the long lanes
    assert pick_lane_T(16384 * LANE_TILE) == 16384
    assert pick_lane_T(32768 * LANE_TILE) == 32768
    assert pick_lane_T(64 << 20) == 32768
    # one symbol past a full grid must fall back to a less padded choice
    assert pick_lane_T(32768 * LANE_TILE + 1) != 32768
    # the pick is always the argmin of the explicit cost model
    for n in (1, 1000, 1 << 20, 2 << 20, (2 << 20) + 1, 4 << 20,
              (4 << 20) + 1, 6 << 20, 48 << 20, 64 << 20):
        def cost(lt):
            n_lanes = (n + lt - 1) // lt
            grid = (n_lanes + LANE_TILE - 1) // LANE_TILE * LANE_TILE
            return grid * lt / _LANE_RATE[lt]
        picked = pick_lane_T(n)
        best = min(_LANE_RATE, key=cost)
        assert cost(picked) <= cost(best) * (1 + 1e-9), (n, picked, best)
