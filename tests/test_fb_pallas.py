"""Pallas forward-backward E-step vs. the XLA rescaled path and the oracle."""

import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops.fb_pallas import batch_stats_pallas
from cpgisland_tpu.ops.forward_backward import batch_stats
from cpgisland_tpu.train import baum_welch, backends
from cpgisland_tpu.utils import chunking


def _random_model(rng, k=8, m=4):
    return HmmParams.from_probs(
        rng.dirichlet(np.ones(k)),
        rng.dirichlet(np.ones(k), size=k),
        rng.dirichlet(np.ones(m), size=k),
    )


def _assert_stats_close(a, b, atol=2e-3):
    np.testing.assert_allclose(np.asarray(a.init), np.asarray(b.init), atol=atol)
    np.testing.assert_allclose(np.asarray(a.trans), np.asarray(b.trans), atol=atol * np.asarray(b.trans).max())
    np.testing.assert_allclose(np.asarray(a.emit), np.asarray(b.emit), atol=atol * np.asarray(b.emit).max())
    np.testing.assert_allclose(float(a.loglik), float(b.loglik), rtol=1e-4)
    assert int(a.n_seqs) == int(b.n_seqs)


def test_matches_xla_rescaled_full_chunks(rng):
    params = _random_model(rng)
    chunks = jnp.asarray(rng.integers(0, 4, size=(5, 256)))
    lengths = jnp.full(5, 256, jnp.int32)
    a = batch_stats_pallas(params, chunks, lengths, t_tile=64)
    b = batch_stats(params, chunks, lengths, mode="rescaled")
    _assert_stats_close(a, b)


def test_matches_xla_padded_and_empty(rng):
    params = _random_model(rng)
    chunks = jnp.asarray(rng.integers(0, 4, size=(4, 200)))
    lengths = jnp.asarray([200, 130, 1, 0], jnp.int32)
    a = batch_stats_pallas(params, chunks, lengths, t_tile=64)
    b = batch_stats(params, chunks, lengths, mode="rescaled")
    _assert_stats_close(a, b)


def test_durbin_preset_structural_zeros(rng):
    params = presets.durbin_cpg8()
    chunks = jnp.asarray(rng.integers(0, 4, size=(3, 192)))
    lengths = jnp.full(3, 192, jnp.int32)
    a = batch_stats_pallas(params, chunks, lengths, t_tile=64)
    B0 = np.asarray(params.B)
    assert (np.asarray(a.emit)[B0 == 0] == 0).all()
    b = batch_stats(params, chunks, lengths, mode="rescaled")
    _assert_stats_close(a, b)


def test_uneven_t_tiling(rng):
    params = _random_model(rng)
    chunks = jnp.asarray(rng.integers(0, 4, size=(2, 250)))  # not a tile multiple
    lengths = jnp.asarray([250, 250], jnp.int32)
    a = batch_stats_pallas(params, chunks, lengths, t_tile=64)
    b = batch_stats(params, chunks, lengths, mode="rescaled")
    _assert_stats_close(a, b)


def test_local_backend_pallas_engine_trains(rng):
    syms = rng.integers(0, 4, size=2048).astype(np.uint8)
    ck = chunking.frame(syms, 256)
    res_x = baum_welch.fit(
        presets.durbin_cpg8(), ck, num_iters=2, convergence=0.0,
        backend=backends.LocalBackend(engine="xla"),
    )
    res_p = baum_welch.fit(
        presets.durbin_cpg8(), ck, num_iters=2, convergence=0.0,
        backend=backends.LocalBackend(engine="pallas"),
    )
    np.testing.assert_allclose(
        np.asarray(res_p.params.A), np.asarray(res_x.params.A), atol=1e-3
    )


def test_spmd_backend_pallas_engine(rng):
    params = _random_model(rng)
    chunks = rng.integers(0, 4, size=(16, 128)).astype(np.uint8)
    ck = chunking.Chunked(
        chunks=chunks, lengths=np.full(16, 128, np.int64), total=16 * 128
    )
    spmd_p = backends.SpmdBackend(engine="pallas")
    spmd_x = backends.SpmdBackend(engine="xla")
    cp, lp = spmd_p.place(ck.chunks, ck.lengths)
    a = spmd_p(params, cp, lp)
    b = spmd_x(params, cp, lp)
    _assert_stats_close(a, b)


def test_engine_validation():
    params = presets.durbin_cpg8()
    with pytest.raises(ValueError, match="rescaled"):
        backends.resolve_fb_engine("pallas", params, "log")
    with pytest.raises(ValueError, match="unknown engine"):
        backends.resolve_fb_engine("bogus", params, "rescaled")


def test_t_not_multiple_of_row_tile(rng):
    """T below the t-tile and not a multiple of 8: the row-tiled forward must
    cover every position (a truncating tile loop once dropped T % 8 rows)."""
    params = presets.durbin_cpg8()
    for T in (250, 7, 63):
        chunks = jnp.asarray(rng.integers(0, 4, size=(4, T), dtype=np.int32).astype(np.uint8))
        lengths = jnp.asarray(rng.integers(1, T + 1, size=4), dtype=jnp.int32)
        got = batch_stats_pallas(params, chunks, lengths)
        want = batch_stats(params, chunks, lengths, mode="rescaled")
        np.testing.assert_allclose(np.asarray(got.trans), np.asarray(want.trans), rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(got.emit), np.asarray(want.emit), rtol=2e-4, atol=2e-4)
        assert float(got.loglik) == pytest.approx(float(want.loglik), abs=0.01)
