"""pipeline.posterior_file + the `posterior` CLI subcommand.

Soft decoding surface: per-position island confidence from the
forward-backward posteriors (the reference exposes only hard Viterbi,
CpGIslandFinder.java:260).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu import cli, pipeline
from cpgisland_tpu.models import presets
from cpgisland_tpu.ops.forward_backward import posterior_decode, posterior_marginals


def _island_fasta(tmp_path, rng):
    fa = tmp_path / "g.fa"
    with open(fa, "w") as f:
        f.write(">c\n")
        parts = []
        for _ in range(2):
            parts.append(rng.choice(list("acgt"), size=2000, p=[0.35, 0.15, 0.15, 0.35]))
            parts.append(rng.choice(list("acgt"), size=700, p=[0.08, 0.42, 0.42, 0.08]))
        s = "".join(np.concatenate(parts))
        for i in range(0, len(s), 70):
            f.write(s[i : i + 70] + "\n")
    return fa, len(s)


def test_posterior_file_matches_ops(tmp_path, rng):
    from cpgisland_tpu.utils import codec

    fa, n = _island_fasta(tmp_path, rng)
    params = presets.durbin_cpg8()
    conf_p = tmp_path / "conf.npy"
    path_p = tmp_path / "mpm.npy"
    res = pipeline.posterior_file(
        str(fa), params, confidence_out=str(conf_p), mpm_path_out=str(path_p)
    )
    assert res.n_symbols == n and res.n_records == 1
    conf = np.load(conf_p)
    mpm = np.load(path_p)
    assert conf.shape == mpm.shape == (n,)

    syms = next(codec.iter_fasta_records(str(fa)))[1]
    gamma, _ = posterior_marginals(params, jnp.asarray(syms))
    np.testing.assert_allclose(
        conf, np.asarray(gamma[:, :4].sum(axis=1)), atol=2e-5
    )
    np.testing.assert_array_equal(mpm, np.asarray(posterior_decode(params, jnp.asarray(syms))))


def test_posterior_confidence_tracks_planted_islands(tmp_path, rng):
    fa, n = _island_fasta(tmp_path, rng)
    conf_p = tmp_path / "conf.npy"
    pipeline.posterior_file(
        str(fa), presets.durbin_cpg8(), confidence_out=str(conf_p)
    )
    conf = np.load(conf_p)
    # Island block 1 spans [2000, 2700); background [0, 2000).
    assert conf[2100:2600].mean() > 0.9
    assert conf[500:1800].mean() < 0.1


def test_posterior_file_rejects_non_base_layout(tmp_path):
    fa = tmp_path / "x.fa"
    fa.write_text(">h\nacgt\n")
    with pytest.raises(ValueError, match="island confidence"):
        pipeline.posterior_file(
            str(fa), presets.two_state_cpg(), confidence_out=str(tmp_path / "c.npy")
        )


def test_posterior_two_state_with_island_states(tmp_path, rng):
    """Non-base-encoding models work when island_states names the columns;
    the CLI rejects the preset without the flag at parse time."""
    fa, n = _island_fasta(tmp_path, rng)
    conf_p = tmp_path / "c.npy"
    res = pipeline.posterior_file(
        str(fa), presets.two_state_cpg(), confidence_out=str(conf_p),
        island_states=(0,),
    )
    assert res.n_symbols == n
    conf = np.load(conf_p)
    assert conf.shape == (n,)
    assert conf[2100:2600].mean() > 0.8  # planted island block
    assert conf[500:1800].mean() < 0.2

    rc = cli.main(["posterior", str(fa), "--confidence-out", str(conf_p),
                   "--preset", "two_state", "--island-states", "0"])
    assert rc == 0
    with pytest.raises(SystemExit):
        cli.main(["posterior", str(fa), "--confidence-out", str(conf_p),
                  "--preset", "two_state"])


def test_posterior_cli(tmp_path, rng):
    fa, n = _island_fasta(tmp_path, rng)
    conf_p = tmp_path / "conf.npy"
    rc = cli.main(["posterior", str(fa), "--confidence-out", str(conf_p)])
    assert rc == 0
    assert np.load(conf_p).shape == (n,)
    # A SIX-token posterior invocation must route to the subcommand parser,
    # not the reference 6-positional-arg compat form (regression: "posterior"
    # was missing from _SUBCOMMANDS and argv[4] got parsed as a float).
    mpm_p = tmp_path / "mpm.npy"
    rc = cli.main(["posterior", str(fa), "--confidence-out", str(conf_p),
                   "--mpm-path-out", str(mpm_p)])
    assert rc == 0
    assert np.load(mpm_p).shape == (n,)


def test_posterior_multi_record_and_span(tmp_path, rng):
    """Two records, one forced through the span path (span passed explicitly,
    smaller than the first record): outputs concatenate in order with
    per-record lengths, and EVERY position — including span boundaries —
    matches the unspanned computation (boundary messages are threaded
    between spans; no DP restart, VERDICT r2 #1)."""
    fa = tmp_path / "m.fa"
    with open(fa, "w") as f:
        for i, nlen in enumerate((2100, 900)):
            f.write(f">r{i}\n")
            s = "".join(rng.choice(list("acgt"), size=nlen))
            for j in range(0, len(s), 70):
                f.write(s[j : j + 70] + "\n")
    conf_p = tmp_path / "conf.npy"
    res = pipeline.posterior_file(
        str(fa), presets.durbin_cpg8(), confidence_out=str(conf_p), span=1500
    )
    assert res.n_records == 2 and res.n_symbols == 3000
    spanned = np.load(conf_p)
    assert spanned.shape == (3000,)
    pipeline.posterior_file(
        str(fa), presets.durbin_cpg8(), confidence_out=str(conf_p)
    )
    full = np.load(conf_p)
    np.testing.assert_allclose(spanned, full, atol=2e-5)


def test_posterior_sharded_matches_oracle(rng):
    """posterior_sharded (XLA lane path, 8-device CPU mesh) vs the
    single-scan posterior_marginals oracle, incl. MPM path."""
    from cpgisland_tpu.parallel.posterior import posterior_sharded

    params = presets.durbin_cpg8()
    obs = rng.choice([0, 1, 2, 3], size=5000, p=[0.3, 0.2, 0.2, 0.3]).astype(np.uint8)
    conf, path = posterior_sharded(
        params, obs, (0, 1, 2, 3), block_size=64, want_path=True
    )
    gamma, _ = posterior_marginals(params, jnp.asarray(obs))
    np.testing.assert_allclose(
        conf, np.asarray(gamma[:, :4].sum(axis=1)), atol=2e-5
    )
    np.testing.assert_array_equal(path, np.asarray(jnp.argmax(gamma, axis=1)))


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_posterior_pallas_engine_matches_oracle(rng):
    """The fused-kernel posterior core (interpret mode off-TPU) vs oracle —
    BOTH branches: want_path=True (alphas*betas assembly) and the production
    want_path=False fast path through _bwd_conf_kernel (betas never stored)."""
    from cpgisland_tpu.ops import fb_pallas

    params = presets.durbin_cpg8()
    obs = rng.choice([0, 1, 2, 3], size=2000, p=[0.3, 0.2, 0.2, 0.3]).astype(np.uint8)
    mask = jnp.asarray((np.arange(8) < 4).astype(np.float32))
    gamma, _ = posterior_marginals(params, jnp.asarray(obs))
    ref = np.asarray(gamma[:, :4].sum(axis=1))
    conf, path = fb_pallas.seq_posterior_pallas(
        params, jnp.asarray(obs), obs.size, mask, want_path=True,
        lane_T=256, t_tile=64,
    )
    np.testing.assert_allclose(np.asarray(conf), ref, atol=2e-5)
    np.testing.assert_array_equal(
        np.asarray(path), np.asarray(jnp.argmax(gamma, axis=1))
    )
    conf_fast, _ = fb_pallas.seq_posterior_pallas(
        params, jnp.asarray(obs), obs.size, mask, want_path=False,
        lane_T=256, t_tile=64,
    )
    np.testing.assert_allclose(np.asarray(conf_fast), ref, atol=2e-5)


def test_npy_stream_writer(tmp_path):
    from cpgisland_tpu.utils.npystream import NpyStreamWriter

    p = tmp_path / "x.npy"
    with NpyStreamWriter(str(p), np.float32) as w:
        w.write(np.arange(5, dtype=np.float32))
        w.write(np.arange(5, 12, dtype=np.float64))  # cast on write
    got = np.load(p)
    np.testing.assert_array_equal(got, np.arange(12, dtype=np.float32))
    assert got.dtype == np.float32
    # Empty writer still produces a loadable (0,) array.
    q = tmp_path / "e.npy"
    NpyStreamWriter(str(q), np.int8).close()
    assert np.load(q).shape == (0,)
    # mmap load works (header is spec-conformant).
    m = np.load(p, mmap_mode="r")
    assert m[7] == 7.0


def test_batch_posterior_pallas_matches_oracle(rng):
    """Chunked-layout batched posterior (one record per lane, interpret mode
    off-TPU) vs the single-scan oracle, ragged lengths included."""
    from cpgisland_tpu.ops.fb_pallas import batch_posterior_pallas

    params = presets.durbin_cpg8()
    sizes = [500, 2000, 1, 1337]
    B, Tpad = 8, 2048
    rows = np.full((B, Tpad), 4, np.uint8)
    recs = []
    for i, n in enumerate(sizes):
        r = rng.choice([0, 1, 2, 3], size=n, p=[0.3, 0.2, 0.2, 0.3]).astype(np.uint8)
        rows[i, :n] = r
        recs.append(r)
    lens = np.zeros(B, np.int32)
    lens[: len(sizes)] = sizes
    mask = jnp.asarray((np.arange(8) < 4).astype(np.float32))
    for want_path in (False, True):
        conf2, path2 = batch_posterior_pallas(
            params, jnp.asarray(rows), jnp.asarray(lens), mask,
            t_tile=64, want_path=want_path,
        )
        for i, r in enumerate(recs):
            gamma, _ = posterior_marginals(params, jnp.asarray(r))
            np.testing.assert_allclose(
                np.asarray(conf2)[i, : r.size],
                np.asarray(gamma[:, :4].sum(axis=1)), atol=2e-5,
            )
            if want_path:
                np.testing.assert_array_equal(
                    np.asarray(path2)[i, : r.size],
                    np.asarray(jnp.argmax(gamma, axis=1)),
                )
        # Padded rows contribute nothing.
        assert np.asarray(conf2)[len(sizes):].sum() == 0.0


def test_posterior_file_batches_small_records(tmp_path, rng):
    """engine='pallas' (interpret off-TPU): a scaffold-heavy file routes
    small records through batched kernel passes (one per pow2 size class —
    the 17000-symbol record lands in its own class), output identical to
    the per-record XLA path and in file order."""
    fa = tmp_path / "m.fa"
    sizes = (900, 400, 17000, 1500, 77, 2100)
    with open(fa, "w") as f:
        for i, n in enumerate(sizes):
            f.write(f">s{i}\n")
            s = "".join(rng.choice(list("acgt"), size=n))
            for j in range(0, len(s), 70):
                f.write(s[j : j + 70] + "\n")
    params = presets.durbin_cpg8()
    c1, c2 = tmp_path / "c1.npy", tmp_path / "c2.npy"
    p1, p2 = tmp_path / "p1.npy", tmp_path / "p2.npy"
    r1 = pipeline.posterior_file(
        str(fa), params, confidence_out=str(c1), mpm_path_out=str(p1),
        engine="pallas",
    )
    r2 = pipeline.posterior_file(
        str(fa), params, confidence_out=str(c2), mpm_path_out=str(p2),
        engine="xla",
    )
    assert r1.n_records == r2.n_records == len(sizes)
    np.testing.assert_allclose(np.load(c1), np.load(c2), atol=2e-5)
    np.testing.assert_array_equal(np.load(p1), np.load(p2))


def test_posterior_islands_out(tmp_path, rng):
    """--islands-out: island calls from the MPM path — the soft counterpart
    of decode.  On a cleanly separable planted-island file the calls must
    essentially agree with the Viterbi-path calls."""
    fa, n = _island_fasta(tmp_path, rng)
    params = presets.durbin_cpg8()
    isl_p = tmp_path / "isl.txt"
    res = pipeline.posterior_file(
        str(fa), params, confidence_out=str(tmp_path / "c.npy"),
        islands_out=str(isl_p),
    )
    assert res.calls is not None and len(res.calls) >= 2
    lines = isl_p.read_text().splitlines()
    assert len(lines) == len(res.calls)
    assert len(lines[0].split()) == 5  # single record: bare reference format
    hard = pipeline.decode_file(str(fa), params, compat=False)
    # Planted islands are unambiguous: same call count, boundaries within a
    # few positions (MPM and Viterbi may disagree at fuzzy edges).
    assert len(res.calls) == len(hard.calls)
    np.testing.assert_allclose(res.calls.beg, hard.calls.beg, atol=8)
    np.testing.assert_allclose(res.calls.end, hard.calls.end, atol=8)

    # two_state + island_states goes through the observation-based caller.
    res2 = pipeline.posterior_file(
        str(fa), presets.two_state_cpg(),
        confidence_out=str(tmp_path / "c2.npy"),
        islands_out=str(tmp_path / "isl2.txt"), island_states=(0,),
    )
    assert res2.calls is not None and len(res2.calls) >= 2

    # CLI surface.
    rc = cli.main([
        "posterior", str(fa), "--confidence-out", str(tmp_path / "c3.npy"),
        "--islands-out", str(tmp_path / "isl3.txt"), "--min-len", "200",
    ])
    assert rc == 0
    assert (tmp_path / "isl3.txt").exists()


def test_posterior_islands_span_not_clipped(tmp_path, rng):
    """An island straddling a posterior span boundary comes out whole (the
    record's MPM path is assembled before calling)."""
    fa = tmp_path / "g.fa"
    with open(fa, "w") as f:
        f.write(">c\n")
        bg = rng.choice(list("acgt"), size=2000, p=[0.35, 0.15, 0.15, 0.35])
        isl = rng.choice(list("acgt"), size=800, p=[0.08, 0.42, 0.42, 0.08])
        bg2 = rng.choice(list("acgt"), size=1800, p=[0.35, 0.15, 0.15, 0.35])
        s = "".join(np.concatenate([bg, isl, bg2]))
        for i in range(0, len(s), 70):
            f.write(s[i : i + 70] + "\n")
    # span=2400 cuts through the island at [2000, 2800).
    res = pipeline.posterior_file(
        str(fa), presets.durbin_cpg8(),
        confidence_out=str(tmp_path / "c.npy"),
        islands_out=str(tmp_path / "i.txt"), span=2400,
    )
    full = pipeline.posterior_file(
        str(fa), presets.durbin_cpg8(),
        confidence_out=str(tmp_path / "c2.npy"),
        islands_out=str(tmp_path / "i2.txt"),
    )
    np.testing.assert_array_equal(res.calls.beg, full.calls.beg)
    np.testing.assert_array_equal(res.calls.end, full.calls.end)
    assert any(b <= 2400 <= e for b, e in zip(res.calls.beg, res.calls.end))


def test_posterior_island_only_no_confidence(tmp_path, rng):
    """islands_out ALONE (VERDICT r3 #4): no per-symbol file is written, the
    calls (host and device engines) are byte-identical to a full run's, and
    the confidence mean is still reported (device: reduced on device, one
    scalar crosses per record)."""
    fa, n = _island_fasta(tmp_path, rng)
    params = presets.durbin_cpg8()
    full = pipeline.posterior_file(
        str(fa), params, confidence_out=str(tmp_path / "c.npy"),
        islands_out=str(tmp_path / "i_full.txt"),
    )
    host = pipeline.posterior_file(
        str(fa), params, islands_out=str(tmp_path / "i_host.txt"),
        island_engine="host",
    )
    dev = pipeline.posterior_file(
        str(fa), params, islands_out=str(tmp_path / "i_dev.txt"),
        island_engine="device",
    )
    ref = (tmp_path / "i_full.txt").read_text()
    assert (tmp_path / "i_host.txt").read_text() == ref
    assert (tmp_path / "i_dev.txt").read_text() == ref
    assert len(full.calls) >= 2
    # No stray per-symbol outputs from the island-only runs.
    stray = [p.name for p in tmp_path.glob("*.npy") if p.name != "c.npy"]
    assert stray == []
    # Host island-only sums the same f64 stream; device reduces in f32.
    assert host.mean_island_confidence == pytest.approx(
        full.mean_island_confidence, rel=1e-12
    )
    assert dev.mean_island_confidence == pytest.approx(
        full.mean_island_confidence, rel=1e-4
    )


def test_posterior_output_validation(tmp_path):
    fa = tmp_path / "x.fa"
    fa.write_text(">h\nacgtacgt\n")
    params = presets.durbin_cpg8()
    with pytest.raises(ValueError, match="nothing to do"):
        pipeline.posterior_file(str(fa), params)
    with pytest.raises(ValueError, match="island_engine"):
        pipeline.posterior_file(
            str(fa), params, islands_out=str(tmp_path / "i.txt"),
            island_engine="gpu",
        )
    # device engine needs islands_out and no host-side path dump
    with pytest.raises(ValueError, match="device"):
        pipeline.posterior_file(
            str(fa), params, confidence_out=str(tmp_path / "c.npy"),
            island_engine="device",
        )
    with pytest.raises(ValueError, match="device"):
        pipeline.posterior_file(
            str(fa), params, islands_out=str(tmp_path / "i.txt"),
            mpm_path_out=str(tmp_path / "p.npy"), island_engine="device",
        )
    # CLI: zero outputs rejected at parse time
    with pytest.raises(SystemExit):
        cli.main(["posterior", str(fa)])


def test_posterior_device_engine_span_parity(tmp_path, rng):
    """Device island engine through the SPAN-THREADED path: spans
    concatenate on device and calls equal the host engine's byte-for-byte;
    writing confidence alongside stays supported (device islands + conf
    fetch coexist)."""
    fa = tmp_path / "g.fa"
    with open(fa, "w") as f:
        f.write(">c\n")
        bg = rng.choice(list("acgt"), size=2000, p=[0.35, 0.15, 0.15, 0.35])
        isl = rng.choice(list("acgt"), size=800, p=[0.08, 0.42, 0.42, 0.08])
        bg2 = rng.choice(list("acgt"), size=1800, p=[0.35, 0.15, 0.15, 0.35])
        s = "".join(np.concatenate([bg, isl, bg2]))
        for i in range(0, len(s), 70):
            f.write(s[i : i + 70] + "\n")
    params = presets.durbin_cpg8()
    pipeline.posterior_file(
        str(fa), params, islands_out=str(tmp_path / "i_host.txt"),
        island_engine="host", span=2400,
    )
    pipeline.posterior_file(
        str(fa), params, islands_out=str(tmp_path / "i_dev.txt"),
        island_engine="device", span=2400,
    )
    dev_conf = pipeline.posterior_file(
        str(fa), params, islands_out=str(tmp_path / "i_dev2.txt"),
        confidence_out=str(tmp_path / "c_dev.npy"),
        island_engine="device", span=2400,
    )
    host_conf = pipeline.posterior_file(
        str(fa), params, islands_out=str(tmp_path / "i_host2.txt"),
        confidence_out=str(tmp_path / "c_host.npy"),
        island_engine="host", span=2400,
    )
    ref = (tmp_path / "i_host.txt").read_text()
    assert (tmp_path / "i_dev.txt").read_text() == ref
    assert (tmp_path / "i_dev2.txt").read_text() == ref
    assert (tmp_path / "i_host2.txt").read_text() == ref
    np.testing.assert_array_equal(
        np.load(tmp_path / "c_dev.npy"), np.load(tmp_path / "c_host.npy")
    )
    assert dev_conf.mean_island_confidence == pytest.approx(
        host_conf.mean_island_confidence, rel=1e-6
    )


def test_posterior_device_engine_batched_parity(tmp_path, rng):
    """Device island engine through the BATCHED small-record path
    (engine='pallas', interpret off-TPU): one flattened device call per
    size-class group, record attribution and calls equal to host."""
    fa = tmp_path / "m.fa"
    sizes = (900, 2600, 1500, 400, 2100)
    with open(fa, "w") as f:
        for i, n in enumerate(sizes):
            f.write(f">s{i}\n")
            parts = [
                rng.choice(list("acgt"), size=n - 300, p=[0.35, 0.15, 0.15, 0.35]),
                rng.choice(list("acgt"), size=300, p=[0.08, 0.42, 0.42, 0.08]),
            ]
            s = "".join(np.concatenate(parts))
            for j in range(0, len(s), 70):
                f.write(s[j : j + 70] + "\n")
    params = presets.durbin_cpg8()
    host = pipeline.posterior_file(
        str(fa), params, islands_out=str(tmp_path / "i_host.txt"),
        island_engine="host", engine="pallas",
    )
    dev = pipeline.posterior_file(
        str(fa), params, islands_out=str(tmp_path / "i_dev.txt"),
        island_engine="device", engine="pallas",
    )
    assert host.n_records == dev.n_records == len(sizes)
    assert len(host.calls) >= 3
    assert (tmp_path / "i_dev.txt").read_text() == (tmp_path / "i_host.txt").read_text()
    np.testing.assert_array_equal(dev.calls.names, host.calls.names)
    assert dev.mean_island_confidence == pytest.approx(
        host.mean_island_confidence, rel=1e-4
    )


def test_posterior_two_state_device_engine(tmp_path, rng):
    """Observation-based (island_states) device calls through posterior."""
    fa, n = _island_fasta(tmp_path, rng)
    host = pipeline.posterior_file(
        str(fa), presets.two_state_cpg(),
        islands_out=str(tmp_path / "i_host.txt"),
        island_states=(0,), island_engine="host",
    )
    dev = pipeline.posterior_file(
        str(fa), presets.two_state_cpg(),
        islands_out=str(tmp_path / "i_dev.txt"),
        island_states=(0,), island_engine="device",
    )
    assert len(host.calls) >= 2
    assert (tmp_path / "i_dev.txt").read_text() == (tmp_path / "i_host.txt").read_text()
