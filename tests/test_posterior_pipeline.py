"""pipeline.posterior_file + the `posterior` CLI subcommand.

Soft decoding surface: per-position island confidence from the
forward-backward posteriors (the reference exposes only hard Viterbi,
CpGIslandFinder.java:260).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu import cli, pipeline
from cpgisland_tpu.models import presets
from cpgisland_tpu.ops.forward_backward import posterior_decode, posterior_marginals


def _island_fasta(tmp_path, rng):
    fa = tmp_path / "g.fa"
    with open(fa, "w") as f:
        f.write(">c\n")
        parts = []
        for _ in range(2):
            parts.append(rng.choice(list("acgt"), size=2000, p=[0.35, 0.15, 0.15, 0.35]))
            parts.append(rng.choice(list("acgt"), size=700, p=[0.08, 0.42, 0.42, 0.08]))
        s = "".join(np.concatenate(parts))
        for i in range(0, len(s), 70):
            f.write(s[i : i + 70] + "\n")
    return fa, len(s)


def test_posterior_file_matches_ops(tmp_path, rng):
    from cpgisland_tpu.utils import codec

    fa, n = _island_fasta(tmp_path, rng)
    params = presets.durbin_cpg8()
    conf_p = tmp_path / "conf.npy"
    path_p = tmp_path / "mpm.npy"
    res = pipeline.posterior_file(
        str(fa), params, confidence_out=str(conf_p), mpm_path_out=str(path_p)
    )
    assert res.n_symbols == n and res.n_records == 1
    conf = np.load(conf_p)
    mpm = np.load(path_p)
    assert conf.shape == mpm.shape == (n,)

    syms = next(codec.iter_fasta_records(str(fa)))[1]
    gamma, _ = posterior_marginals(params, jnp.asarray(syms))
    np.testing.assert_allclose(
        conf, np.asarray(gamma[:, :4].sum(axis=1)), atol=2e-5
    )
    np.testing.assert_array_equal(mpm, np.asarray(posterior_decode(params, jnp.asarray(syms))))


def test_posterior_confidence_tracks_planted_islands(tmp_path, rng):
    fa, n = _island_fasta(tmp_path, rng)
    conf_p = tmp_path / "conf.npy"
    pipeline.posterior_file(
        str(fa), presets.durbin_cpg8(), confidence_out=str(conf_p)
    )
    conf = np.load(conf_p)
    # Island block 1 spans [2000, 2700); background [0, 2000).
    assert conf[2100:2600].mean() > 0.9
    assert conf[500:1800].mean() < 0.1


def test_posterior_file_rejects_non_base_layout(tmp_path):
    fa = tmp_path / "x.fa"
    fa.write_text(">h\nacgt\n")
    with pytest.raises(ValueError, match="island confidence"):
        pipeline.posterior_file(
            str(fa), presets.two_state_cpg(), confidence_out=str(tmp_path / "c.npy")
        )


def test_posterior_two_state_with_island_states(tmp_path, rng):
    """Non-base-encoding models work when island_states names the columns;
    the CLI rejects the preset without the flag at parse time."""
    fa, n = _island_fasta(tmp_path, rng)
    conf_p = tmp_path / "c.npy"
    res = pipeline.posterior_file(
        str(fa), presets.two_state_cpg(), confidence_out=str(conf_p),
        island_states=(0,),
    )
    assert res.n_symbols == n
    conf = np.load(conf_p)
    assert conf.shape == (n,)
    assert conf[2100:2600].mean() > 0.8  # planted island block
    assert conf[500:1800].mean() < 0.2

    rc = cli.main(["posterior", str(fa), "--confidence-out", str(conf_p),
                   "--preset", "two_state", "--island-states", "0"])
    assert rc == 0
    with pytest.raises(SystemExit):
        cli.main(["posterior", str(fa), "--confidence-out", str(conf_p),
                  "--preset", "two_state"])


def test_posterior_cli(tmp_path, rng):
    fa, n = _island_fasta(tmp_path, rng)
    conf_p = tmp_path / "conf.npy"
    rc = cli.main(["posterior", str(fa), "--confidence-out", str(conf_p)])
    assert rc == 0
    assert np.load(conf_p).shape == (n,)
    # A SIX-token posterior invocation must route to the subcommand parser,
    # not the reference 6-positional-arg compat form (regression: "posterior"
    # was missing from _SUBCOMMANDS and argv[4] got parsed as a float).
    mpm_p = tmp_path / "mpm.npy"
    rc = cli.main(["posterior", str(fa), "--confidence-out", str(conf_p),
                   "--mpm-path-out", str(mpm_p)])
    assert rc == 0
    assert np.load(mpm_p).shape == (n,)


def test_posterior_multi_record_and_span(tmp_path, rng):
    """Two records, one forced through the span path (span passed explicitly,
    smaller than the first record): outputs concatenate in order with
    per-record lengths, and the non-boundary positions match the unspanned
    computation."""
    fa = tmp_path / "m.fa"
    with open(fa, "w") as f:
        for i, nlen in enumerate((2100, 900)):
            f.write(f">r{i}\n")
            s = "".join(rng.choice(list("acgt"), size=nlen))
            for j in range(0, len(s), 70):
                f.write(s[j : j + 70] + "\n")
    conf_p = tmp_path / "conf.npy"
    res = pipeline.posterior_file(
        str(fa), presets.durbin_cpg8(), confidence_out=str(conf_p), span=1500
    )
    assert res.n_records == 2 and res.n_symbols == 3000
    spanned = np.load(conf_p)
    assert spanned.shape == (3000,)
    pipeline.posterior_file(
        str(fa), presets.durbin_cpg8(), confidence_out=str(conf_p)
    )
    full = np.load(conf_p)
    # Away from the record-1 span boundary at 1500, the restart's effect
    # decays — interior positions agree with the exact computation.
    np.testing.assert_allclose(spanned[:1400], full[:1400], atol=1e-4)
    np.testing.assert_allclose(spanned[2100:], full[2100:], atol=1e-4)
