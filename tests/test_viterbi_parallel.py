"""Parallel blockwise Viterbi vs the sequential scan decoder (exactness)."""

import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops import viterbi as V
from cpgisland_tpu.ops import viterbi_parallel as VP


def _random_model(rng, k=3, m=4):
    pi = rng.dirichlet(np.ones(k))
    A = rng.dirichlet(np.ones(k), size=k)
    B = rng.dirichlet(np.ones(m), size=k)
    return HmmParams.from_probs(pi, A, B)


def _path_score(params, obs, path):
    lp = np.asarray(params.log_pi)
    lA = np.asarray(params.log_A)
    lB = np.asarray(params.log_B)
    s = lp[path[0]] + lB[path[0], obs[0]]
    for t in range(1, len(obs)):
        s += lA[path[t - 1], path[t]] + lB[path[t], obs[t]]
    return s


@pytest.mark.parametrize("T,block", [(1, 4), (2, 4), (5, 4), (16, 4), (17, 4), (64, 8), (100, 16), (257, 32)])
def test_matches_sequential_scores_and_validity(rng, T, block):
    for _ in range(3):
        params = _random_model(rng)
        obs = jnp.asarray(rng.integers(0, 4, size=T))
        p_seq, s_seq = V.viterbi(params, obs)
        p_par, s_par = VP.viterbi_parallel(params, obs, block_size=block)
        assert float(s_par) == pytest.approx(float(s_seq), abs=2e-2, rel=1e-5)
        # The parallel path must achieve the optimal score too.
        got = _path_score(params, np.asarray(obs), np.asarray(p_par))
        assert got == pytest.approx(float(s_seq), abs=2e-2, rel=1e-5)


def test_durbin_exact_path_agreement(rng):
    # One-hot emissions make the Durbin model effectively tie-free on
    # CG-structured input; paths should agree exactly.
    params = presets.durbin_cpg8()
    bg = rng.choice([0, 3], size=500)
    island = np.tile([1, 2], 150)
    obs = jnp.asarray(np.concatenate([bg, island, bg]).astype(np.int32))
    p_seq = np.asarray(V.viterbi(params, obs, return_score=False))
    p_par = np.asarray(VP.viterbi_parallel(params, obs, block_size=64, return_score=False))
    np.testing.assert_array_equal(p_seq, p_par)


def test_pad_passthrough(rng):
    params = _random_model(rng)
    obs = rng.integers(0, 4, size=70)
    full, s_full = VP.viterbi_parallel(params, jnp.asarray(obs), block_size=16)
    padded = np.concatenate([obs, np.full(30, 4)]).astype(np.int32)
    p, s = VP.viterbi_parallel(params, jnp.asarray(padded), block_size=16)
    assert float(s) == pytest.approx(float(s_full), abs=1e-3)
    got = _path_score(params, obs, np.asarray(p)[:70])
    assert got == pytest.approx(float(s_full), abs=1e-3)


def test_batch_matches_single(rng):
    params = presets.durbin_cpg8()
    chunks = rng.integers(0, 4, size=(4, 96)).astype(np.int32)
    chunks[3, 50:] = 4  # padded tail
    lengths = np.array([96, 96, 96, 50], dtype=np.int32)
    batch = VP.viterbi_parallel_batch(
        params, jnp.asarray(chunks), jnp.asarray(lengths), block_size=16, return_score=False
    )
    for i in range(4):
        single = VP.viterbi_parallel(params, jnp.asarray(chunks[i]), block_size=16, return_score=False)
        np.testing.assert_array_equal(np.asarray(batch[i]), np.asarray(single))


def test_block_size_invariance(rng):
    params = _random_model(rng, k=5)
    obs = jnp.asarray(rng.integers(0, 4, size=200))
    ref, s_ref = VP.viterbi_parallel(params, obs, block_size=8)
    for b in (16, 32, 200, 512):
        p, s = VP.viterbi_parallel(params, obs, block_size=b)
        assert float(s) == pytest.approx(float(s_ref), abs=2e-2)
        got = _path_score(params, np.asarray(obs), np.asarray(p))
        assert got == pytest.approx(float(s_ref), abs=2e-2)


def test_long_sequence_smoke(rng):
    params = presets.durbin_cpg8()
    obs = jnp.asarray(rng.integers(0, 4, size=1 << 16))
    p_par, s_par = VP.viterbi_parallel(params, obs)
    p_seq, s_seq = V.viterbi(params, obs)
    # f32 reduction order differs between the two algorithms; exact path
    # equality below is the strong check.
    assert float(s_par) == pytest.approx(float(s_seq), rel=1e-4)
    # On genuinely random input ties are astronomically unlikely with this model.
    assert (np.asarray(p_par) == np.asarray(p_seq)).mean() > 0.999
