"""Parallel blockwise Viterbi vs the sequential scan decoder (exactness)."""

import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import HmmParams
from cpgisland_tpu.ops import viterbi as V
from cpgisland_tpu.ops import viterbi_parallel as VP



def _random_model(rng, k=3, m=4):
    pi = rng.dirichlet(np.ones(k))
    A = rng.dirichlet(np.ones(k), size=k)
    B = rng.dirichlet(np.ones(m), size=k)
    return HmmParams.from_probs(pi, A, B)


def _path_score(params, obs, path):
    lp = np.asarray(params.log_pi)
    lA = np.asarray(params.log_A)
    lB = np.asarray(params.log_B)
    s = lp[path[0]] + lB[path[0], obs[0]]
    for t in range(1, len(obs)):
        s += lA[path[t - 1], path[t]] + lB[path[t], obs[t]]
    return s


@pytest.mark.parametrize("T,block", [(1, 4), (2, 4), (5, 4), (16, 4), (17, 4), (64, 8), (100, 16), (257, 32)])
def test_matches_sequential_scores_and_validity(rng, T, block):
    for _ in range(3):
        params = _random_model(rng)
        obs = jnp.asarray(rng.integers(0, 4, size=T))
        p_seq, s_seq = V.viterbi(params, obs)
        p_par, s_par = VP.viterbi_parallel(params, obs, block_size=block)
        assert float(s_par) == pytest.approx(float(s_seq), abs=2e-2, rel=1e-5)
        # The parallel path must achieve the optimal score too.
        got = _path_score(params, np.asarray(obs), np.asarray(p_par))
        assert got == pytest.approx(float(s_seq), abs=2e-2, rel=1e-5)


def test_durbin_exact_path_agreement(rng):
    # One-hot emissions make the Durbin model effectively tie-free on
    # CG-structured input; paths should agree exactly.
    params = presets.durbin_cpg8()
    bg = rng.choice([0, 3], size=500)
    island = np.tile([1, 2], 150)
    obs = jnp.asarray(np.concatenate([bg, island, bg]).astype(np.int32))
    p_seq = np.asarray(V.viterbi(params, obs, return_score=False))
    p_par = np.asarray(VP.viterbi_parallel(params, obs, block_size=64, return_score=False))
    np.testing.assert_array_equal(p_seq, p_par)


def test_pad_passthrough(rng):
    params = _random_model(rng)
    obs = rng.integers(0, 4, size=70)
    full, s_full = VP.viterbi_parallel(params, jnp.asarray(obs), block_size=16)
    padded = np.concatenate([obs, np.full(30, 4)]).astype(np.int32)
    p, s = VP.viterbi_parallel(params, jnp.asarray(padded), block_size=16)
    assert float(s) == pytest.approx(float(s_full), abs=1e-3)
    got = _path_score(params, obs, np.asarray(p)[:70])
    assert got == pytest.approx(float(s_full), abs=1e-3)


def test_batch_matches_single(rng):
    params = presets.durbin_cpg8()
    chunks = rng.integers(0, 4, size=(4, 96)).astype(np.int32)
    chunks[3, 50:] = 4  # padded tail
    lengths = np.array([96, 96, 96, 50], dtype=np.int32)
    batch = VP.viterbi_parallel_batch(
        params, jnp.asarray(chunks), jnp.asarray(lengths), block_size=16, return_score=False
    )
    for i in range(4):
        single = VP.viterbi_parallel(params, jnp.asarray(chunks[i]), block_size=16, return_score=False)
        np.testing.assert_array_equal(np.asarray(batch[i]), np.asarray(single))


def test_block_size_invariance(rng):
    params = _random_model(rng, k=5)
    obs = jnp.asarray(rng.integers(0, 4, size=200))
    ref, s_ref = VP.viterbi_parallel(params, obs, block_size=8)
    for b in (16, 32, 200, 512):
        p, s = VP.viterbi_parallel(params, obs, block_size=b)
        assert float(s) == pytest.approx(float(s_ref), abs=2e-2)
        got = _path_score(params, np.asarray(obs), np.asarray(p))
        assert got == pytest.approx(float(s_ref), abs=2e-2)


def test_f32_range_normalization_survives_huge_magnitudes(rng):
    """Regression for the genome-scale f32 range bug: score chains grow
    ~-1.3/symbol, so an UNnormalized prefix-product chain reaches magnitudes
    where the f32 ulp dwarfs the O(1) per-state differences argmax decisions
    ride on.  This model makes every step cost ~-5e3, so 20k steps reach
    -1e8 (ulp 8) — without scan_block_products' per-combine normalization
    the cross-block entering vectors quantize and the path is garbage; with
    it the decode must match the float64 DP exactly (structure is tie-free)."""
    K, M, T, block = 3, 4, 20_000, 128
    pref = rng.normal(size=(K, M)) * 2.0
    params = HmmParams(
        log_pi=jnp.asarray(rng.normal(size=K), jnp.float32),
        log_A=jnp.asarray(np.log(rng.dirichlet(np.ones(K), size=K)), jnp.float32),
        log_B=jnp.asarray(pref - 5000.0, jnp.float32),
    )
    obs = rng.integers(0, M, size=T).astype(np.int32)
    p_par = np.asarray(
        VP.viterbi_parallel(params, jnp.asarray(obs), block_size=block,
                            return_score=False)
    )
    # float64 DP oracle with backpointers.
    lp = np.asarray(params.log_pi, np.float64)
    lA = np.asarray(params.log_A, np.float64)
    lB = np.asarray(params.log_B, np.float64)
    delta = lp + lB[:, obs[0]]
    bps = np.zeros((T, K), np.int64)
    for t in range(1, T):
        scores = delta[:, None] + lA
        bps[t] = scores.argmax(axis=0)
        delta = scores.max(axis=0) + lB[:, obs[t]]
    path = np.zeros(T, np.int64)
    path[-1] = delta.argmax()
    for t in range(T - 1, 0, -1):
        path[t - 1] = bps[t, path[t]]
    np.testing.assert_array_equal(p_par, path)
    # The sequential decoder (per-step normalized delta + Kahan offset) must
    # survive the same magnitudes.
    p_seq, s_seq = V.viterbi(params, jnp.asarray(obs))
    np.testing.assert_array_equal(np.asarray(p_seq), path)
    assert float(s_seq) == pytest.approx(float(delta.max()), rel=1e-6)


def _path_score_f64(params, obs, path):
    """Exact (float64) log-score of a decoded path — the ground-truth judge
    when the two f32 engines disagree on near-ties."""
    lp = np.asarray(params.log_pi, np.float64)
    lA = np.asarray(params.log_A, np.float64)
    lB = np.asarray(params.log_B, np.float64)
    return lp[path[0]] + lB[path, obs].sum() + lA[path[:-1], path[1:]].sum()


def test_long_sequence_smoke(rng):
    params = presets.durbin_cpg8()
    obs = jnp.asarray(rng.integers(0, 4, size=1 << 16))
    p_par, s_par = VP.viterbi_parallel(params, obs)
    p_seq, s_seq = V.viterbi(params, obs)
    # f32 reduction order differs between the two algorithms; the f64
    # re-score below is the strong check.
    assert float(s_par) == pytest.approx(float(s_seq), rel=1e-4)
    p_par, p_seq, obs_np = np.asarray(p_par), np.asarray(p_seq), np.asarray(obs)
    # Both f32 engines resolve near-ties differently (~0.1% of positions at
    # this length); the strong check is that each path's f64 score sits at
    # the f64-DP optimum to within the engines' accumulated f32 error.
    assert (p_par == p_seq).mean() > 0.99
    lp = np.asarray(params.log_pi, np.float64)
    lA = np.asarray(params.log_A, np.float64)
    lB = np.asarray(params.log_B, np.float64)
    delta = lp + lB[:, obs_np[0]]
    for t in range(1, obs_np.size):
        delta = (delta[:, None] + lA).max(axis=0) + lB[:, obs_np[t]]
    s_opt = delta.max()
    assert _path_score_f64(params, obs_np, p_par) == pytest.approx(s_opt, abs=0.05)
    assert _path_score_f64(params, obs_np, p_seq) == pytest.approx(s_opt, abs=0.05)
