"""Tests for the external-driver interface (__graft_entry__.py).

This is the one surface the round driver calls (entry() compile check +
dryrun_multichip() sharding check), so it gets direct coverage in all the
configurations the driver can invoke it from:

1. in-process, with the virtual 8-device CPU mesh already provisioned
   (this suite's conftest) — the fast path;
2. from a parent process whose JAX is initialized on a *different* platform
   with too few devices — the self-provisioning subprocess path, which is
   exactly the shape that failed in round 1 (MULTICHIP_r01.json ok=false);
3. failure propagation from the subprocess.
"""

import os

import pytest
import subprocess
import sys
import textwrap

import jax
import numpy as np

import __graft_entry__ as ge

from conftest import require_devices

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_entry_compiles_and_runs():
    fn, args = ge.entry()
    out = fn(*args)
    paths = np.asarray(out)
    assert paths.shape == (4, 16384)
    assert paths.min() >= 0 and paths.max() < 8
    # jittable: lower/compile explicitly, as the driver's compile check does.
    fn.lower(*args).compile()


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_dryrun_inprocess_on_virtual_mesh():
    require_devices(8)
    ge.dryrun_multichip(8)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_dryrun_self_provisions_from_foreign_platform():
    """Run dryrun_multichip(8) from a parent whose JAX has only 1 CPU device
    (no host_platform_device_count), mimicking the driver process with JAX
    already initialized on the single real TPU chip.  dryrun_multichip must
    provision its own virtual mesh via subprocess re-exec and succeed."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""  # parent: 1 CPU device only
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    script = textwrap.dedent(
        """
        import jax
        assert len(jax.devices()) < 8, "test precondition: parent must be device-poor"
        import __graft_entry__ as ge
        ge.dryrun_multichip(8)
        print("PARENT_OK")
        """
    )
    proc = subprocess.run(
        [sys.executable, "-c", script],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=1200,
    )
    assert proc.returncode == 0, f"stdout={proc.stdout}\nstderr={proc.stderr}"
    assert "PARENT_OK" in proc.stdout


def test_dryrun_subprocess_failure_propagates(monkeypatch):
    """A failing dry-run body must surface as a raised error, not a silent
    green — the round-1 bug was exactly an unreported failure mode."""
    monkeypatch.setattr(
        ge.subprocess,
        "run",
        lambda *a, **k: subprocess.CompletedProcess(a, 1, stdout="boom", stderr="bad"),
    )
    # Force the subprocess path regardless of how many devices this process has.
    monkeypatch.setattr(jax, "devices", lambda *a, **k: [None])
    try:
        ge.dryrun_multichip(8)
    except RuntimeError as e:
        assert "rc=1" in str(e) and "boom" in str(e)
    else:
        raise AssertionError("expected RuntimeError from failed subprocess")


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_main_dryrun_cli_form():
    """The subprocess re-exec invokes `__graft_entry__.py --dryrun N`; check
    that exact command line works end to end with the provisioning env."""
    env = ge._force_cpu_mesh_env(8, os.environ)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "__graft_entry__.py"), "--dryrun", "8"],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=1200,
    )
    assert proc.returncode == 0, f"stdout={proc.stdout}\nstderr={proc.stderr}"
    assert "dryrun_multichip(8) ok" in proc.stdout
