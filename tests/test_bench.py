"""The bench harness's measurement-integrity machinery.

bench.py is a driver contract (the round harness runs it and records the
JSON line), and r4 hardened it against a real failure mode: the TPU relay
serving phantom ~0 ms "results" without executing (see CLAUDE.md).  These
tests pin the defenses — phantom detection, the plausibility ceiling, the
capture-artifact discovery — plus a tiny end-to-end smoke of two bench
configs on CPU so a broken harness fails the suite, not the driver run.
"""

import os
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import bench  # noqa: E402


def test_best_wall_rejects_persistent_phantoms():
    """Sub-100us reps are phantoms: retried a few times, then fatal."""
    calls = []

    def instant(seed):
        calls.append(seed)

    with pytest.raises(RuntimeError, match="phantom"):
        bench._best_wall(instant)
    # every attempt used a DISTINCT seed (no byte-identical requests)
    assert len(calls) == len(set(calls)) > 1


def test_best_wall_takes_min_over_distinct_seeds():
    seen = []

    def fn(seed):
        seen.append(seed)
        time.sleep(0.01 if len(seen) % 2 else 0.05)

    best = bench._best_wall(fn, reps=3)
    assert 0.009 < best < 0.05  # min picked; generous for loaded runners
    assert len(seen) == len(set(seen)) == 3


def test_plausibility_ceiling():
    assert bench._check_plausible(1e9, "x") == 1e9
    with pytest.raises(RuntimeError, match="phantom"):
        bench._check_plausible(1e12, "x")


def test_per_path_plausibility_ceiling():
    """VERDICT r4 #6: every benched path has a tight ceiling (2.5x its
    enforced BASELINE.md figure) so a phantom 5x inflation raises."""
    ceilings = bench._path_ceilings()
    for path in bench._baseline_key_by_path():
        assert path in ceilings, f"no BASELINE.md marker resolved for {path}"
        # Tighter than the global net, looser than the published figure.
        assert ceilings[path] < bench.PLAUSIBLE_MAX_SYM_PER_S
        enforced = ceilings[path] / bench.PATH_CEILING_FACTOR
        with pytest.raises(RuntimeError, match="phantom"):
            bench._check_plausible(5.0 * enforced, path)
        assert bench._check_plausible(1.2 * enforced, path) == 1.2 * enforced


def test_armed_ceilings_record_in_artifact():
    """VERDICT r5 #7: every bench phase emits what it ACTUALLY armed —
    the per-path ceilings, or an explicit degradation marker when the
    BASELINE.md markers failed to parse (never a silent fallback)."""
    rec = bench.armed_ceilings_record()
    assert isinstance(rec, dict)
    for path in bench._baseline_key_by_path():
        assert path in rec
        assert rec[path] == round(bench._path_ceilings()[path] / 1e6, 1)
    old = bench._PATH_CEILINGS
    try:
        bench._PATH_CEILINGS = {}
        assert bench.armed_ceilings_record() == "degraded-to-global"
    finally:
        bench._PATH_CEILINGS = old


def test_capture_paths_newest_round(tmp_path):
    import pubnum

    for r in ("r02", "r04", "r03"):
        (tmp_path / f"bench_captured_{r}.stderr.txt").write_text("x")
        (tmp_path / f"bench_captured_{r}.stdout.json").write_text("{}")
    stderr_p, stdout_p, rnd = pubnum.capture_paths(str(tmp_path))
    assert rnd == 4
    assert stderr_p.endswith("bench_captured_r04.stderr.txt")
    assert stdout_p.endswith("bench_captured_r04.stdout.json")


def test_parse_lines_covers_every_pattern():
    """Each published stderr line format parses to its figure key — a
    renamed log line would silently drop its key from enforcement."""
    import pubnum

    lines = [
        "decode[pallas]: 1131.8 Msym/s (240 ms / 256 MiB, chained x6)",
        "decode-2state[pallas]: 2149.1 Msym/s (125 ms)",
        "em[pallas]: 917.6 Msym/s/iter (35 ms)",
        "em-2state[pallas]: 1185.9 Msym/s/iter (14 ms)",
        "em-seq[auto]: 364.7 Msym/s/iter (181 ms)",
        "em-seq2d[auto]: 428.4 Msym/s/iter (117 ms)",
        "span-decode[auto]: 14.7 Msym/s user-path wall (...)",
        "span-posterior[auto]: 11.1 Msym/s user-path wall (...)",
        "batched-decode[pallas]: 743.8 Msym/s (...)",
        "posterior[pallas]: 513.6 Msym/s (...)",
        "projected v5e-8 north-star workload: 0.67 s (decode 0.34 s + "
        "10 EM iters 0.34 s)",
    ]
    vals = pubnum.parse_lines(lines)
    for key in (
        "decode_msym", "decode2_msym", "em_msym", "em2_msym", "em_seq_msym",
        "em_seq2d_msym", "span_decode_msym", "span_posterior_msym",
        "batched_msym", "posterior_msym", "northstar_s",
        "northstar_decode_s", "northstar_em_s",
    ):
        assert key in vals, key
    assert vals["em_seq_msym"] == 364.7
    assert vals["span_decode_msym"] == 14.7


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_bench_decode_and_em_smoke():
    """Tiny CPU smoke of the two configs the DRIVER runs every round."""
    d = bench.bench_decode(1 << 17, engine="auto", chain=2)
    e = bench.bench_em(2, chunk_size=1 << 12, engine="auto", chain=2)
    assert 0 < d < bench.PLAUSIBLE_MAX_SYM_PER_S
    assert 0 < e < bench.PLAUSIBLE_MAX_SYM_PER_S


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_span_bench_asserts_continuity(monkeypatch):
    """The span config is a correctness gate, not just a timer: a path with
    NO island crossing the boundary must fail its assertion."""
    rng = np.random.default_rng(0)
    n, span = 1 << 15, 1 << 14
    obs = bench._planted_record(n, span, rng)
    # Remove the boundary-straddling island: pure AT around the boundary.
    obs[span - 8192 : span + 8192] = 3
    monkeypatch.setattr(
        bench, "_planted_record", lambda n, boundary, rng: obs
    )
    with pytest.raises(AssertionError, match="crosses the span boundary"):
        bench.bench_span_decode(n, span, engine="auto")
