"""The reduced one-hot FB kernels vs the dense fused path (exactness).

ops.fb_onehot reduces the probability-space boundary-message products (and,
with it, the whole-sequence posterior / exact-EM paths that consume them)
to 2x2 for one-hot-emission models.  Unlike the max-plus case the reduction
is exact without caveats — dropped terms are multiplications by exact f32
zeros — so parity here is tight: conf/stat outputs agree with the dense
engine to f32 rounding of the renormalization scalars (~1e-6), and the
consumed DIRECTION of a transfer operator agrees even though the raw
matrices differ in never-consumed out-of-group rows.

Off-TPU these run the XLA twins; the TPU suite run exercises the Pallas
kernels against the same assertions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpgisland_tpu.models import presets
from cpgisland_tpu.models.hmm import HmmParams, sample_sequence
from cpgisland_tpu.ops import fb_onehot, fb_pallas
from cpgisland_tpu.parallel.posterior import posterior_sharded, resolve_fb_engine
from cpgisland_tpu.train.backends import SeqBackend
from cpgisland_tpu.utils import chunking

MASK8 = jnp.asarray(np.r_[np.ones(4), np.zeros(4)].astype(np.float32))


def _obs(rng, n):
    params = presets.durbin_cpg8()
    _, obs = sample_sequence(params, jax.random.PRNGKey(int(rng.integers(1 << 30))), n)
    return params, obs


def test_supports():
    assert fb_onehot.supports(presets.durbin_cpg8())
    rng = np.random.default_rng(0)
    dense = HmmParams.from_probs(
        rng.dirichlet(np.ones(4)),
        rng.dirichlet(np.ones(4), size=4),
        rng.dirichlet(np.ones(4), size=4),
    )
    assert not fb_onehot.supports(dense)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_posterior_conf_parity(rng):
    params, obs = _obs(rng, 30000)
    c_d, _ = fb_pallas.seq_posterior_pallas(
        params, obs, obs.shape[0], MASK8, lane_T=4096, t_tile=512
    )
    c_o, _ = fb_pallas.seq_posterior_pallas(
        params, obs, obs.shape[0], MASK8, lane_T=4096, t_tile=512, onehot=True
    )
    np.testing.assert_allclose(np.asarray(c_d), np.asarray(c_o), atol=2e-5)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_posterior_want_path_parity(rng):
    params, obs = _obs(rng, 20000)
    c_d, p_d = fb_pallas.seq_posterior_pallas(
        params, obs, obs.shape[0], MASK8, lane_T=4096, t_tile=512, want_path=True
    )
    c_o, p_o = fb_pallas.seq_posterior_pallas(
        params, obs, obs.shape[0], MASK8, lane_T=4096, t_tile=512,
        want_path=True, onehot=True,
    )
    np.testing.assert_allclose(np.asarray(c_d), np.asarray(c_o), atol=2e-5)
    assert np.array_equal(np.asarray(p_d), np.asarray(p_o))


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_continuation_span_parity(rng):
    """first=False spans with threaded enter/exit directions and prev_sym."""
    params, obs = _obs(rng, 24000)
    span = 12000
    piece = obs[span:]
    enter = np.abs(np.random.default_rng(1).normal(size=8)).astype(np.float32)
    enter /= enter.sum()
    kwargs = dict(
        enter_dir=jnp.asarray(enter), exit_dir=None, first=False,
        lane_T=4096, t_tile=512,
    )
    c_d, _ = fb_pallas.seq_posterior_pallas(
        params, piece, piece.shape[0], MASK8, **kwargs
    )
    c_o, _ = fb_pallas.seq_posterior_pallas(
        params, piece, piece.shape[0], MASK8,
        onehot=True, prev_sym=jnp.int32(int(obs[span - 1])), **kwargs
    )
    np.testing.assert_allclose(np.asarray(c_d), np.asarray(c_o), atol=2e-5)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_transfer_total_consumed_direction(rng):
    """Raw operators differ in never-consumed rows; the consumed direction
    (in-group entering dir @ total) must agree — first AND continuation."""
    params, obs = _obs(rng, 16000)
    pi = np.exp(np.asarray(params.log_pi))
    B = np.exp(np.asarray(params.log_B))
    for first, prev in ((True, 0), (False, int(obs[4095]))):
        piece = obs if first else obs[4096:]
        t_d = np.asarray(
            fb_pallas.seq_transfer_total_pallas(
                params, piece, piece.shape[0], first=first, lane_T=4096
            )
        )
        t_o = np.asarray(
            fb_pallas.seq_transfer_total_pallas(
                params, piece, piece.shape[0], first=first, lane_T=4096,
                onehot=True, prev_sym=jnp.int32(prev),
            )
        )
        v = pi * B[:, int(piece[0])] if first else pi * B[:, prev]
        v = (v / v.sum()).astype(np.float32)
        d_d = v @ t_d
        d_o = v @ t_o
        np.testing.assert_allclose(
            d_d / d_d.sum(), d_o / d_o.sum(), atol=2e-6
        )


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_seq_stats_parity(rng):
    params, obs = _obs(rng, 40000)
    s_d = fb_pallas.seq_stats_pallas(params, obs, obs.shape[0], lane_T=4096)
    s_o = fb_pallas.seq_stats_pallas(
        params, obs, obs.shape[0], lane_T=4096, onehot=True
    )
    np.testing.assert_allclose(np.asarray(s_d.init), np.asarray(s_o.init), atol=1e-5)
    # 5e-5 rel: on TPU the reduced path's stats come from the in-kernel
    # two-level summation while the dense path reduces via XLA einsums —
    # different f32 accumulation orders over T terms (and ~2e-5-rel TPU
    # transcendentals) put agreement at tolerance level, not bit level.
    np.testing.assert_allclose(
        np.asarray(s_d.trans), np.asarray(s_o.trans), rtol=5e-5, atol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(s_d.emit), np.asarray(s_o.emit), rtol=5e-5, atol=1e-3
    )
    assert float(s_d.loglik) == pytest.approx(float(s_o.loglik), rel=1e-5)


def test_seq_backend_onehot(rng):
    """SeqBackend(engine='onehot') over the 8-device mesh matches 'xla'."""
    params, obs = _obs(rng, 8 * 4096)
    chunked = chunking.Chunked(
        chunks=np.asarray(obs)[None, :],
        lengths=np.asarray([obs.shape[0]], np.int32),
        total=obs.shape[0],
    )
    stats = {}
    for eng in ("xla", "onehot"):
        backend = SeqBackend(engine=eng, lane_T=512, t_tile=256)
        prepared = backend.prepare(chunked)
        o, l = backend.place(prepared.chunks, prepared.lengths)
        stats[eng] = backend(params, o, l)
    for f in ("init", "trans", "emit"):
        np.testing.assert_allclose(
            np.asarray(getattr(stats["xla"], f)),
            np.asarray(getattr(stats["onehot"], f)),
            rtol=1e-4, atol=1e-3,
        )


def test_posterior_sharded_onehot(rng):
    """Sharded posterior over the 8-device mesh, onehot vs xla engines."""
    params, obs = _obs(rng, 8 * 2048 + 77)
    c_x, _ = posterior_sharded(
        params, np.asarray(obs), (0, 1, 2, 3), engine="xla", block_size=256
    )
    c_o, _ = posterior_sharded(
        params, np.asarray(obs), (0, 1, 2, 3), engine="onehot",
        lane_T=512, t_tile=256,
    )
    np.testing.assert_allclose(np.asarray(c_x), np.asarray(c_o), atol=2e-5)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_posterior_file_span_onehot(tmp_path, rng):
    """End-to-end: posterior_file's span threading (prev_sym included) with
    the onehot engine matches the dense engine and the unspanned run."""
    from cpgisland_tpu import pipeline

    params, obs = _obs(rng, 3000)
    seq = "".join("ACGT"[s] for s in np.asarray(obs))
    fa = tmp_path / "t.fa"
    fa.write_text(f">r1\n{seq}\n")
    outs = {}
    for eng, span in (("pallas", 1500), ("onehot", 1500), ("onehot", 1 << 20)):
        conf_p = tmp_path / f"c_{eng}_{span}.npy"
        pipeline.posterior_file(
            str(fa), params, confidence_out=str(conf_p), span=span, engine=eng
        )
        outs[(eng, span)] = np.load(conf_p)
    np.testing.assert_allclose(
        outs[("onehot", 1500)], outs[("pallas", 1500)], atol=2e-5
    )
    np.testing.assert_allclose(
        outs[("onehot", 1500)], outs[("onehot", 1 << 20)], atol=2e-5
    )


def test_resolve_fb_engine_validation():
    rng = np.random.default_rng(1)
    dense = HmmParams.from_probs(
        rng.dirichlet(np.ones(4)),
        rng.dirichlet(np.ones(4), size=4),
        rng.dirichlet(np.ones(4), size=4),
    )
    with pytest.raises(ValueError, match="onehot"):
        resolve_fb_engine("onehot", dense)
    expected = "onehot" if jax.default_backend() == "tpu" else "xla"
    assert resolve_fb_engine("auto", presets.durbin_cpg8()) == expected


def test_pick_lane_T_onehot_cost_model():
    """Pin the reduced-kernel lane cost model at grid boundaries, like the
    dense twin's test (test_fb_pallas) — a rate re-sweep must not silently
    start over-padding small inputs or exceed the 65536 exact-EM compile
    ceiling the table caps at."""
    from cpgisland_tpu.ops.fb_pallas import (
        LANE_TILE,
        _LANE_RATE_ONEHOT,
        pick_lane_T,
    )

    assert pick_lane_T(1, onehot=True) == 8192
    # exactly full grids pick the long lanes
    assert pick_lane_T(65536 * LANE_TILE, onehot=True) == 65536
    # the 131072 entry needs the explicit long_lanes opt-in: it is safe only
    # for paths that stay on reduced kernels end to end — the XLA
    # assemblies over [Tp, K, NL] streams fail to remote-compile there.
    assert pick_lane_T(131072 * LANE_TILE, onehot=True) == 65536
    assert pick_lane_T(131072 * LANE_TILE, onehot=True, long_lanes=True) == 131072
    # one symbol past a full grid must fall back to a less padded choice
    assert pick_lane_T(65536 * LANE_TILE + 1, onehot=True) != 65536
    # the pick is always the argmin of the explicit cost model
    for long_lanes in (False, True):
        table = {
            k: v for k, v in _LANE_RATE_ONEHOT.items()
            if long_lanes or k <= 65536
        }
        for n in (1, 1000, 1 << 20, 2 << 20, (2 << 20) + 1, 8 << 20,
                  (8 << 20) + 1, 48 << 20, 64 << 20, 128 << 20):
            def cost(lt):
                n_lanes = (n + lt - 1) // lt
                grid = (n_lanes + LANE_TILE - 1) // LANE_TILE * LANE_TILE
                return grid * lt / table[lt]
            picked = pick_lane_T(n, onehot=True, long_lanes=long_lanes)
            best = min(table, key=cost)
            assert cost(picked) <= cost(best) * (1 + 1e-9), (n, picked, best)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_batch_stats_parity(rng):
    """Chunked-path batch_stats_pallas(onehot=True) vs dense.

    auto routes the chunked E-step here too (train.backends.resolve_fb_engine)
    since the reduced-stream stats kernel landed — the scatter+dense-stats
    variant this path briefly used had regressed, see the resolver comment."""
    params = presets.durbin_cpg8()
    N, T = 5, 3000
    chunks = np.zeros((N, T), np.uint8)
    lengths = np.asarray([3000, 2500, 1, 0, 3000], np.int32)
    for i in range(N):
        if lengths[i]:
            _, o = sample_sequence(params, jax.random.PRNGKey(i), int(lengths[i]))
            chunks[i, : lengths[i]] = np.asarray(o)
    s_d = fb_pallas.batch_stats_pallas(
        params, jnp.asarray(chunks), jnp.asarray(lengths), t_tile=512
    )
    s_o = fb_pallas.batch_stats_pallas(
        params, jnp.asarray(chunks), jnp.asarray(lengths), t_tile=512, onehot=True
    )
    np.testing.assert_allclose(np.asarray(s_d.init), np.asarray(s_o.init), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(s_d.trans), np.asarray(s_o.trans), rtol=1e-5, atol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(s_d.emit), np.asarray(s_o.emit), rtol=1e-5, atol=1e-3
    )
    assert float(s_d.loglik) == pytest.approx(float(s_o.loglik), rel=1e-6)
    assert int(s_d.n_seqs) == int(s_o.n_seqs)


@pytest.mark.slow  # tier-1 budget rebalance: >7 s CPU call (full suite + ci_checks slices still run it)
def test_batch_posterior_parity(rng):
    """Batched small-record posterior, onehot vs dense, conf AND path."""
    params = presets.durbin_cpg8()
    N, T = 4, 2000
    chunks = np.zeros((N, T), np.uint8)
    lengths = np.asarray([2000, 1500, 1, 2000], np.int32)
    for i in range(N):
        _, o = sample_sequence(params, jax.random.PRNGKey(10 + i), int(lengths[i]))
        chunks[i, : lengths[i]] = np.asarray(o)
    for want_path in (False, True):
        c_d, p_d = fb_pallas.batch_posterior_pallas(
            params, jnp.asarray(chunks), jnp.asarray(lengths), MASK8,
            want_path=want_path,
        )
        c_o, p_o = fb_pallas.batch_posterior_pallas(
            params, jnp.asarray(chunks), jnp.asarray(lengths), MASK8,
            want_path=want_path, onehot=True,
        )
        for i in range(N):
            L = int(lengths[i])
            np.testing.assert_allclose(
                np.asarray(c_d)[i, :L], np.asarray(c_o)[i, :L], atol=2e-5
            )
            if want_path:
                assert np.array_equal(
                    np.asarray(p_d)[i, :L], np.asarray(p_o)[i, :L]
                )
