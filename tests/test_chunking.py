"""Chunk framing tests incl. the reference drop-remainder quirk (java:130,256)."""

import numpy as np
import pytest

from cpgisland_tpu.utils import chunking


def test_exact_multiple():
    syms = np.arange(12, dtype=np.uint8) % 4
    ck = chunking.frame(syms, 4)
    assert ck.num_chunks == 3 and ck.total == 12
    assert (ck.lengths == 4).all()
    np.testing.assert_array_equal(ck.chunks.reshape(-1), syms)


def test_drop_remainder_compat():
    syms = np.arange(10, dtype=np.uint8) % 4
    ck = chunking.frame(syms, 4, drop_remainder=True)
    assert ck.num_chunks == 2 and ck.total == 8  # trailing 2 symbols dropped


def test_pad_remainder_clean():
    syms = np.arange(10, dtype=np.uint8) % 4
    ck = chunking.frame(syms, 4)
    assert ck.num_chunks == 3 and ck.total == 10
    assert ck.lengths.tolist() == [4, 4, 2]
    assert (ck.chunks[2, 2:] == chunking.PAD_SYMBOL).all()


def test_all_dropped():
    ck = chunking.frame(np.zeros(3, dtype=np.uint8), 4, drop_remainder=True)
    assert ck.num_chunks == 0 and ck.total == 0


def test_pad_to_multiple():
    syms = np.zeros(12, dtype=np.uint8)
    ck = chunking.pad_to_multiple(chunking.frame(syms, 4), 8)
    assert ck.num_chunks == 8
    assert ck.lengths.tolist() == [4, 4, 4, 0, 0, 0, 0, 0]
    assert ck.total == 12
    # already a multiple -> unchanged
    assert chunking.pad_to_multiple(ck, 4).num_chunks == 8


def test_reference_constants():
    assert chunking.TRAIN_CHUNK == 0x10000
    assert chunking.DECODE_CHUNK == 0x100000


def test_bucket_records_shapes_and_budget():
    """bucket_records: pow2 size classes, per-group allocation bounded by
    max(budget, one padded record) — NOT records x max_len (VERDICT r2 #2)."""
    from cpgisland_tpu.utils.chunking import bucket_records

    rng = np.random.default_rng(0)
    sizes = [100, 900, 1000, 70_000, 200, 300_000, 50]
    records = [rng.integers(0, 4, size=n).astype(np.uint8) for n in sizes]
    budget = 4096
    b = bucket_records(iter(records), floor=1024, budget=budget, pad_value=4)
    assert b.total == sum(sizes)
    assert b.num_chunks == len(sizes)
    # No allocation is records x max_len; each group obeys the budget (or is
    # a single over-budget record padded to its own pow2).
    for c in b.chunks:
        assert c.shape[0] * c.shape[1] <= max(budget, c.shape[1])
        assert (c.shape[1] & (c.shape[1] - 1)) == 0 and c.shape[1] >= 1024
    # Every record is recoverable from its bucket row (order within a size
    # class follows arrival order).
    seen = []
    for c, l in zip(b.chunks, b.lengths):
        for i, n in enumerate(l):
            seen.append((c.shape[1], np.asarray(c[i, :n])))
    by_class: dict = {}
    for n, r in zip(sizes, records):
        T = 1024
        while T < n:
            T <<= 1
        by_class.setdefault(T, []).append(r)
    got_by_class: dict = {}
    for T, row in seen:
        got_by_class.setdefault(T, []).append(row)
    for T, rows in by_class.items():
        assert len(got_by_class[T]) == len(rows)
        for a, g in zip(rows, got_by_class[T]):
            np.testing.assert_array_equal(a, g)


def test_bucket_records_empty_raises():
    from cpgisland_tpu.utils.chunking import bucket_records

    with pytest.raises(ValueError):
        bucket_records(iter([]))
