"""Chunk framing tests incl. the reference drop-remainder quirk (java:130,256)."""

import numpy as np
import pytest

from cpgisland_tpu.utils import chunking


def test_exact_multiple():
    syms = np.arange(12, dtype=np.uint8) % 4
    ck = chunking.frame(syms, 4)
    assert ck.num_chunks == 3 and ck.total == 12
    assert (ck.lengths == 4).all()
    np.testing.assert_array_equal(ck.chunks.reshape(-1), syms)


def test_drop_remainder_compat():
    syms = np.arange(10, dtype=np.uint8) % 4
    ck = chunking.frame(syms, 4, drop_remainder=True)
    assert ck.num_chunks == 2 and ck.total == 8  # trailing 2 symbols dropped


def test_pad_remainder_clean():
    syms = np.arange(10, dtype=np.uint8) % 4
    ck = chunking.frame(syms, 4)
    assert ck.num_chunks == 3 and ck.total == 10
    assert ck.lengths.tolist() == [4, 4, 2]
    assert (ck.chunks[2, 2:] == chunking.PAD_SYMBOL).all()


def test_all_dropped():
    ck = chunking.frame(np.zeros(3, dtype=np.uint8), 4, drop_remainder=True)
    assert ck.num_chunks == 0 and ck.total == 0


def test_pad_to_multiple():
    syms = np.zeros(12, dtype=np.uint8)
    ck = chunking.pad_to_multiple(chunking.frame(syms, 4), 8)
    assert ck.num_chunks == 8
    assert ck.lengths.tolist() == [4, 4, 4, 0, 0, 0, 0, 0]
    assert ck.total == 12
    # already a multiple -> unchanged
    assert chunking.pad_to_multiple(ck, 4).num_chunks == 8


def test_reference_constants():
    assert chunking.TRAIN_CHUNK == 0x10000
    assert chunking.DECODE_CHUNK == 0x100000
