#!/usr/bin/env python
"""Repo-root launcher for graftcheck (``python tools/graftcheck.py``).

Defaults to linting the whole checkout's package; equivalent to
``python -m cpgisland_tpu.analysis`` once the package is importable.
"""

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from cpgisland_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
