"""Published-number single-sourcing (VERDICT r2 #6).

Every performance figure in README.md / BASELINE.md is wrapped in an inline
marker:

    <!--num:decode_msym-->1151.7<!--/num-->

and must equal the value parsed from the captured bench artifact
(``bench_captured_r03.stderr.txt`` + ``.stdout.json`` — the verbatim streams
of ONE ``python bench.py --extended`` run on the real chip).  The test
``tests/test_published_numbers.py`` runs :func:`check_docs` so a hand-edited
figure can never drift from the artifact; ``python tools/pubnum.py --write``
re-derives every marker in place after capturing a fresh run.

The driver's own ``BENCH_r{N}.json`` carries the same stderr tail, so the
judge can cross-check the artifact against the driver's record; the test
additionally asserts the north-star seconds in the LATEST driver file agree
with the docs within a variance band.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DOCS = ("README.md", "BASELINE.md")


def capture_paths(repo: str = REPO) -> tuple:
    """(stderr_path, stdout_path, round) of the NEWEST captured artifact.

    Discovered, not hardcoded: tests/test_published_numbers.py additionally
    fails when this round lags the newest driver BENCH_r*.json — a stale
    capture can't silently keep certifying new code (VERDICT r3 #8)."""
    cands = sorted(glob.glob(os.path.join(repo, "bench_captured_r*.stderr.txt")))
    if not cands:
        raise FileNotFoundError("no bench_captured_r*.stderr.txt artifact")
    stderr_p = cands[-1]
    rnd = int(re.search(r"_r(\d+)\.stderr\.txt$", stderr_p).group(1))
    return stderr_p, stderr_p.replace(".stderr.txt", ".stdout.json"), rnd

_LINE_PATTERNS = {
    "decode_msym": r"^decode\[\w+\]:\s+([\d.]+) Msym/s",
    "decode2_msym": r"^decode-2state\[\w+\]:\s+([\d.]+) Msym/s",
    "em_msym": r"^em\[\w+\]:\s+([\d.]+) Msym/s/iter",
    "em2_msym": r"^em-2state\[\w+\]:\s+([\d.]+) Msym/s/iter",
    "batched_msym": r"^batched-decode\[\w+\]:\s+([\d.]+) Msym/s",
    "posterior_msym": r"^posterior\[\w+\]:\s+([\d.]+) Msym/s",
    "em_seq_msym": r"^em-seq\[\w+\]:\s+([\d.]+) Msym/s/iter",
    "em_seq2d_msym": r"^em-seq2d\[\w+\]:\s+([\d.]+) Msym/s/iter",
    "span_decode_msym": r"^span-decode\[\w+\]:\s+([\d.]+) Msym/s",
    "span_posterior_msym": r"^span-posterior\[\w+\]:\s+([\d.]+) Msym/s",
    "northstar_s": r"^projected v5e-8 north-star workload:\s+([\d.]+) s",
    "northstar_decode_s": r"north-star workload:.*\(decode ([\d.]+) s",
    "northstar_em_s": r"north-star workload:.*10 EM iters ([\d.]+) s\)",
}

_NUM_RE = re.compile(r"<!--num:([\w.]+)-->([-\d.]+)<!--/num-->")


def parse_lines(lines) -> dict:
    """Figure dict from bench stderr lines (shared by the captured-artifact
    parse and the driver-tail cross-check in test_published_numbers.py)."""
    vals: dict = {}
    for line in lines:
        line = line.strip()
        for key, pat in _LINE_PATTERNS.items():
            m = re.search(pat, line)
            if m:
                vals[key] = float(m.group(1))
        if line.startswith("extended: "):
            vals.update(json.loads(line[len("extended: "):]))
        m = re.match(r"end-to-end \([\d]+ Mbase file\): (\{.*\})", line)
        if m:
            vals.update(
                {f"e2e_{k}": v for k, v in json.loads(m.group(1)).items()}
            )
    return vals


def parse_captured(repo: str = REPO) -> dict:
    """Canonical figure dict from the captured artifact pair."""
    stderr_p, stdout_p, rnd = capture_paths(repo)
    with open(stderr_p) as f:
        vals = parse_lines(f)
    vals["capture_round"] = rnd
    with open(stdout_p) as f:
        out = json.loads(f.read().strip())
    vals["northstar_value"] = out["value"]
    vals["vs_baseline"] = out["vs_baseline"]
    # Derived convenience figures used in prose.
    vals["decode_gsym_8chip"] = round(vals["decode_msym"] * 8 / 1000, 1)
    vals["decode2_gsym"] = round(vals["decode2_msym"] / 1000, 2)
    vals["encode_gsym"] = round(vals["e2e_encode_msym_per_s"] / 1000, 2)
    vals["cached_encode_gsym"] = round(
        vals["e2e_cached_encode_msym_per_s"] / 1000, 2
    )
    return vals


def check_docs(vals: dict, repo: str = REPO) -> list:
    """Every <!--num:key--> span in the docs must match vals[key] exactly
    (string-equal after float round-trip).  Returns a list of problems."""
    problems = []
    seen_any = False
    for doc in DOCS:
        text = open(os.path.join(repo, doc)).read()
        for m in _NUM_RE.finditer(text):
            seen_any = True
            key, shown = m.group(1), m.group(2)
            if key not in vals:
                problems.append(f"{doc}: unknown figure key {key!r}")
                continue
            want = vals[key]
            try:
                ok = float(shown) == float(want)
            except ValueError:
                ok = False
            if not ok:
                problems.append(
                    f"{doc}: <!--num:{key}--> shows {shown} but the captured "
                    f"artifact says {want}"
                )
    if not seen_any:
        problems.append("no <!--num:...--> markers found in any doc")
    return problems


def write_docs(vals: dict, repo: str = REPO) -> int:
    """Rewrite every marker's number from the artifact; returns #updates."""
    n = 0
    for doc in DOCS:
        path = os.path.join(repo, doc)
        text = open(path).read()

        def sub(m):
            nonlocal n
            key = m.group(1)
            if key not in vals:
                return m.group(0)
            n += 1
            return f"<!--num:{key}-->{vals[key]}<!--/num-->"

        new = _NUM_RE.sub(sub, text)
        if new != text:
            open(path, "w").write(new)
    return n


if __name__ == "__main__":
    vals = parse_captured()
    if "--write" in sys.argv:
        print(f"updated {write_docs(vals)} figures")
    problems = check_docs(vals)
    for p in problems:
        print("DRIFT:", p)
    sys.exit(1 if problems else 0)
