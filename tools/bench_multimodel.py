"""Multi-model stacking A/B harness: stacked vs sequential launch sets.

The r12 tentpole (BASELINE.md "Multi-model occupancy") stacks M family
members' reduced chains along a model axis inside ONE kernel launch set —
the multi-model generalization of the r9 fwd/bwd co-schedule, aimed at the
same per-pass fixed chain-drain cost the r8 attribution measured.  This
harness is the honest ship-or-negative A/B (the bench_passfusion.py
discipline): identical inputs, BIT-IDENTITY-gated per member before any
timing, chained reps with params-side seed folds, per-path plausibility
ceilings — run it on the capturing TPU before trusting committed ratios.

Phases (each stacked-vs-sequential over the SAME stream and M members):
  posterior — M members' conf tracks off one record
              (seq_posterior_pallas_stacked vs M sequential cores)
  em        — M members' chunked E-step + M-step
              (batch_stats_pallas_stacked vs M sequential batch passes)
  decode    — M members' flat batched decode
              (decode_batch_flat_stacked vs M sequential flat decodes)

Relay rules (CLAUDE.md): chained reps inside one jit, a DISTINCT seed
folded into every rep (params-side, so shared symbol streams/preps stay
valid), every rep fetches a small output, ceilings = the enforced
BASELINE.md markers x2.5 via obs.watchdog (model-symbols/s is gated by
M x the per-path ceiling — a stack cannot outrun M ideal members).

Usage:
  python tools/bench_multimodel.py                        # TPU capture
  python tools/bench_multimodel.py --platform cpu --smoke # CI slice
Prints ONE JSON line on stdout; progress goes to stderr.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _best_wall(fn, reps: int) -> float:
    """Min wall over reps with DISTINCT seeds; sub-100us walls are relay
    phantoms and retried (bench.py defense)."""
    seed, done, phantoms, best = 1, 0, 0, float("inf")
    while done < reps:
        t0 = time.perf_counter()
        fn(seed)
        dt = time.perf_counter() - t0
        seed += 1
        if dt < 1e-4:
            phantoms += 1
            if phantoms > 3 * reps:
                raise RuntimeError("persistent ~0 ms results: relay phantom")
            continue
        best = min(best, dt)
        done += 1
    return best


def _check_ceiling(tput: float, ceiling: float, what: str) -> None:
    if tput > ceiling:
        raise RuntimeError(
            f"{what}: {tput / 1e6:.0f} Msym/s exceeds the "
            f"{ceiling / 1e6:.0f} Msym/s plausibility ceiling (relay phantom?)"
        )


def _jitter(p, s):
    # Params-side fold (full seed, no small modulus — bench_passfusion's
    # rationale): the shared symbol stream and any prepared artifacts stay
    # byte-identical across reps while every rep's program inputs differ.
    import jax.numpy as jnp

    return dataclasses.replace(
        p, log_pi=p.log_pi - s.astype(jnp.float32) * 1e-7
    )


def _members(n_members: int):
    import jax

    from cpgisland_tpu.models import presets

    out = [presets.durbin_cpg8()]
    for i in range(1, n_members):
        out.append(presets.random_hmm(jax.random.PRNGKey(i), 8, 4, partition=2))
    return tuple(out)


def bench_posterior(members, n, *, chain, reps, ceiling, lane_T, t_tile):
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.ops import fb_pallas

    M = len(members)
    rng = np.random.default_rng(1)
    obs = jnp.asarray(rng.integers(0, 4, size=n, dtype=np.int32).astype(np.uint8))
    mask = jnp.asarray(np.r_[np.ones(4), np.zeros(4)].astype(np.float32))
    masks = (mask,) * M

    # Bit-identity gate per member BEFORE any timing.
    conf_st, _ = fb_pallas.seq_posterior_pallas_stacked(
        members, obs, n, masks, lane_T=lane_T, t_tile=t_tile
    )
    for m, p in enumerate(members):
        conf_1, _ = fb_pallas.seq_posterior_pallas(
            p, obs, n, mask, lane_T=lane_T, t_tile=t_tile, onehot=True
        )
        if not bool(jnp.all(conf_st[m] == conf_1)):
            raise AssertionError(
                f"posterior member {m}: stacked != sequential (bit-identity "
                "contract broken)"
            )
    log(f"posterior parity gate: {M} members bit-identical")

    @jax.jit
    def run_stacked(ps, obs, s):
        ps = tuple(_jitter(p, s) for p in ps)

        def body(c, _):
            # Carry folds into the masks so reps are DATA-DEPENDENT (XLA
            # must not hoist/CSE the loop body — bench_passfusion's
            # `mask + c * 0.0` discipline).
            conf, _ = fb_pallas.seq_posterior_pallas_stacked(
                ps, obs, n, tuple(m + c * 0.0 for m in masks),
                lane_T=lane_T, t_tile=t_tile,
            )
            return c + jnp.sum(conf[:, :8]) * 1e-9, None

        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=chain)
        return c

    @jax.jit
    def run_sequential(ps, obs, s):
        ps = tuple(_jitter(p, s) for p in ps)

        def body(c, _):
            for p in ps:
                conf, _ = fb_pallas.seq_posterior_pallas(
                    p, obs, n, mask + c * 0.0, lane_T=lane_T,
                    t_tile=t_tile, onehot=True,
                )
                c = c + jnp.sum(conf[:8]) * 1e-9
            return c, None

        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=chain)
        return c

    out = {"members": M}
    for arm, fn in (("sequential", run_sequential), ("stacked", run_stacked)):
        jax.block_until_ready(fn(members, obs, jnp.int32(0)))
        best = _best_wall(
            lambda s, fn=fn: float(
                jax.device_get(fn(members, obs, jnp.int32(s)))
            ),
            reps,
        ) / chain
        tput = n * M / best
        _check_ceiling(tput, ceiling * M, "posterior(model-symbols)")
        out[arm] = round(tput / 1e6, 1)
        log(f"posterior [{arm}]: {tput / 1e6:8.1f} Msym/s model-symbols "
            f"({best * 1e3:.2f} ms)")
    out["ratio"] = round(out["stacked"] / out["sequential"], 3)
    return out


def bench_em(members, n, *, chain, reps, ceiling, chunk=1 << 16):
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.ops import fb_pallas
    from cpgisland_tpu.train.baum_welch import em_update

    M = len(members)
    rng = np.random.default_rng(2)
    n_chunks = max(1, n // chunk)
    chunks = jnp.asarray(
        rng.integers(0, 4, size=(n_chunks, chunk), dtype=np.int32).astype(np.uint8)
    )
    lengths = jnp.full(n_chunks, chunk, jnp.int32)
    total = n_chunks * chunk

    st = fb_pallas.batch_stats_pallas_stacked(members, chunks, lengths)
    for m, p in enumerate(members):
        ref = fb_pallas.batch_stats_pallas(p, chunks, lengths, onehot=True)
        for f in ("init", "trans", "emit", "loglik"):
            if not bool(jnp.all(getattr(st[m], f) == getattr(ref, f))):
                raise AssertionError(
                    f"em member {m}: stacked != sequential {f} "
                    "(bit-identity contract broken)"
                )
    log(f"em parity gate: {M} members bit-identical")

    @jax.jit
    def run_stacked(ps, chunks, lengths, s):
        ps = tuple(_jitter(p, s) for p in ps)

        def body(ps, _):
            stats = fb_pallas.batch_stats_pallas_stacked(ps, chunks, lengths)
            return tuple(
                em_update(p, st)[0] for p, st in zip(ps, stats)
            ), None

        ps, _ = jax.lax.scan(body, ps, None, length=chain)
        return ps[0].log_pi

    @jax.jit
    def run_sequential(ps, chunks, lengths, s):
        ps = tuple(_jitter(p, s) for p in ps)

        def body(ps, _):
            out = []
            for p in ps:
                st = fb_pallas.batch_stats_pallas(
                    p, chunks, lengths, onehot=True
                )
                out.append(em_update(p, st)[0])
            return tuple(out), None

        ps, _ = jax.lax.scan(body, ps, None, length=chain)
        return ps[0].log_pi

    out = {"members": M, "n_chunks": n_chunks}
    for arm, fn in (("sequential", run_sequential), ("stacked", run_stacked)):
        jax.block_until_ready(fn(members, chunks, lengths, jnp.int32(0)))
        best = _best_wall(
            lambda s, fn=fn: np.asarray(
                jax.device_get(fn(members, chunks, lengths, jnp.int32(s)))
            ).sum(),
            reps,
        ) / chain
        tput = total * M / best
        _check_ceiling(tput, ceiling * M, "em(model-symbols)")
        out[arm] = round(tput / 1e6, 1)
        log(f"em [{arm}]: {tput / 1e6:8.1f} Msym/s/iter model-symbols "
            f"({best * 1e3:.2f} ms)")
    out["ratio"] = round(out["stacked"] / out["sequential"], 3)
    return out


def bench_decode(members, n, *, chain, reps, ceiling, bk=4096, T=1 << 16):
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.ops import viterbi_onehot as OH

    M = len(members)
    rng = np.random.default_rng(3)
    N = max(1, n // T)
    chunks = jnp.asarray(rng.integers(0, 4, size=(N, T), dtype=np.int32))
    lengths = jnp.full(N, T, jnp.int32)
    total = N * T
    S = members[0].n_symbols
    P = min(8191, T - 1)

    paths_st = OH.decode_batch_flat_stacked(members, chunks, lengths, block_size=bk)
    for m, p in enumerate(members):
        ref = OH.decode_batch_flat(p, chunks, lengths, block_size=bk)
        if not bool(jnp.all(paths_st[m] == ref)):
            raise AssertionError(
                f"decode member {m}: stacked != sequential paths "
                "(bit-identity contract broken)"
            )
    log(f"decode parity gate: {M} members bit-identical")

    def perturb(c, s):
        # Decode has no params-side jitter that keeps paths comparable:
        # perturb ONE symbol with a large-period seed map (bench_passfusion).
        pos = 1 + (s * 7) % P
        return c.at[0, pos].set((c[0, pos] + 1 + s // P) % S)

    @jax.jit
    def run_stacked(chunks, s):
        c0 = perturb(chunks, s)

        def body(c, _):
            # Value-preserving carry fold: the stream becomes loop-carried
            # so XLA cannot hoist the body out of the chain.
            ci = c0 + (c * 0.0).astype(c0.dtype)
            paths = OH.decode_batch_flat_stacked(
                members, ci, lengths, block_size=bk
            )
            return c + jnp.sum(paths[:, 0, :8]).astype(jnp.float32) * 1e-9, None

        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=chain)
        return c

    @jax.jit
    def run_sequential(chunks, s):
        c0 = perturb(chunks, s)

        def body(c, _):
            ci = c0 + (c * 0.0).astype(c0.dtype)
            for p in members:
                paths = OH.decode_batch_flat(p, ci, lengths, block_size=bk)
                c = c + jnp.sum(paths[0, :8]).astype(jnp.float32) * 1e-9
            return c, None

        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=chain)
        return c

    out = {"members": M, "records": N}
    for arm, fn in (("sequential", run_sequential), ("stacked", run_stacked)):
        jax.block_until_ready(fn(chunks, jnp.int32(0)))
        best = _best_wall(
            lambda s, fn=fn: float(jax.device_get(fn(chunks, jnp.int32(s)))),
            reps,
        ) / chain
        tput = total * M / best
        _check_ceiling(tput, ceiling * M, "decode(model-symbols)")
        out[arm] = round(tput / 1e6, 1)
        log(f"decode [{arm}]: {tput / 1e6:8.1f} Msym/s model-symbols "
            f"({best * 1e3:.2f} ms)")
    out["ratio"] = round(out["stacked"] / out["sequential"], 3)
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="auto")
    ap.add_argument("--mib", type=int, default=16)
    ap.add_argument("--members", type=int, default=3)
    ap.add_argument("--chain", type=int, default=6)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--t-tile", type=int, default=512)
    ap.add_argument(
        "--smoke", action="store_true",
        help="tiny CPU sizes: bit-identity gates + one timing rep per arm",
    )
    args = ap.parse_args()

    import jax

    if args.platform != "auto":
        jax.config.update("jax_platforms", args.platform)

    from cpgisland_tpu.obs import watchdog
    from cpgisland_tpu.ops import fb_pallas

    members = _members(args.members)
    on_tpu = jax.default_backend() == "tpu"
    if args.smoke:
        n = 128 << 10
        chain, reps = 2, 1
        lane_T = 2048
    elif not on_tpu:
        # CPU projection: bit-identity + structure only — a serial machine
        # cannot observe chain-latency overlap, so ratios here are NOT the
        # chip answer (BASELINE.md "Multi-model occupancy").
        n = min(args.mib, 2) << 20
        chain, reps = 2, 2
        lane_T = 8192
    else:
        n = args.mib << 20
        chain, reps = args.chain, args.reps
        lane_T = fb_pallas.pick_lane_T(n, onehot=True, long_lanes=False)
    ceilings = watchdog.path_ceilings() if on_tpu else {}
    inf = float("inf")

    results = {
        "bench": "multimodel",
        "backend": jax.default_backend(),
        "n_mi": n >> 20,
        "members": args.members,
        "chain": chain,
        "projection": not on_tpu,
    }
    results["posterior"] = bench_posterior(
        members, n, chain=chain, reps=reps,
        ceiling=ceilings.get("posterior", inf),
        lane_T=lane_T, t_tile=args.t_tile,
    )
    results["em"] = bench_em(
        members, n, chain=chain, reps=reps,
        ceiling=ceilings.get("em", inf),
        chunk=(1 << 16) if n >= (1 << 20) else max(1024, n // 4),
    )
    results["decode"] = bench_decode(
        members, n, chain=chain, reps=reps,
        ceiling=ceilings.get("decode", inf),
        bk=4096 if on_tpu else 512,
        T=(1 << 16) if n >= (1 << 20) else max(2048, n // 4),
    )
    print(json.dumps(results))
    return 0


if __name__ == "__main__":
    sys.exit(main())
