"""fleet_top: render a serve daemon's fleet health + SLO state from its
metrics JSONL.

    python tools/fleet_top.py metrics.jsonl            # one-shot render
    python tools/fleet_top.py metrics.jsonl --watch 2  # re-render every 2 s

Input is the JSONL the daemon writes under ``--metrics`` (with
``--metrics-interval`` supplying periodic ``slo_snapshot`` records).  The
renderer shows, per device: the health-state timeline reconstructed from
``device_quarantined`` / ``device_restored`` transitions, requeues off the
device, and its tagged throughput share — followed by the latest queue
depth / backpressure / latency percentiles from the newest snapshot.  Pure
file reading: no live process, no sockets (use ``kind=stats`` on the wire
for a live probe).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def collect(path: str) -> dict:
    devices: dict = {}
    snapshot = None
    t0 = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # clipped tail line
            ev = rec.get("event")
            ts = rec.get("ts")
            if t0 is None and ts is not None:
                t0 = ts
            if ev in ("device_quarantined", "device_restored"):
                dev = devices.setdefault(
                    rec.get("device", "?"), {"timeline": [], "requeues": 0}
                )
                state = (
                    "QUARANTINED" if ev == "device_quarantined" else "HEALTHY"
                )
                dev["timeline"].append({
                    "t": (ts - t0) if (ts is not None and t0 is not None) else None,
                    "state": state,
                    "reason": rec.get("reason"),
                    "cooldown_s": rec.get("cooldown_s"),
                })
            elif ev == "flush_requeued":
                dev = devices.setdefault(
                    rec.get("device", "?"), {"timeline": [], "requeues": 0}
                )
                dev["requeues"] += 1
            elif ev == "slo_snapshot":
                snapshot = rec  # last one wins
    return {"devices": devices, "snapshot": snapshot, "t0": t0}


def render(state: dict) -> str:
    lines = []
    snap = state["snapshot"]
    devices = dict(state["devices"])
    # Fold per-device throughput + fleet health from the newest snapshot.
    fleet = (snap or {}).get("fleet") or {}
    for label, dstat in (fleet.get("devices") or {}).items():
        devices.setdefault(label, {"timeline": [], "requeues": 0})[
            "health"] = dstat
    thr = (((snap or {}).get("slo") or {}).get("throughput") or {})
    for label, share in (thr.get("device") or {}).items():
        if label == "-":
            continue
        devices.setdefault(label, {"timeline": [], "requeues": 0})[
            "throughput"] = share
    if devices:
        lines.append("devices:")
        for label in sorted(devices):
            d = devices[label]
            h = d.get("health") or {}
            cur = h.get("state", "healthy" if not d["timeline"]
                        else d["timeline"][-1]["state"].lower())
            tp = d.get("throughput") or {}
            lines.append(
                f"  {label:<8} {cur:<12} quarantines={h.get('quarantines', 0)} "
                f"restores={h.get('restores', 0)} requeues_off={d['requeues']} "
                f"served={tp.get('requests', 0)} req / {tp.get('symbols', 0)} sym"
            )
            for tr in d["timeline"]:
                at = "" if tr["t"] is None else f"+{tr['t']:.1f}s "
                why = f" ({tr['reason']})" if tr.get("reason") else ""
                lines.append(f"    {at}-> {tr['state']}{why}")
    else:
        lines.append("devices: none seen (single-worker daemon, or no "
                     "health transitions yet)")
    if snap is not None:
        stats = snap.get("stats") or {}
        slo = snap.get("slo") or {}
        lat = slo.get("latency_s") or {}
        lines.append("")
        lines.append(
            f"queue: {stats.get('queued_requests', '?')} request(s) / "
            f"{stats.get('queued_symbols', '?')} symbol(s) queued, "
            f"backpressure={stats.get('backpressure', '?')}, "
            f"flushes={stats.get('flushes', '?')}"
        )
        if lat.get("count"):
            lines.append(
                f"latency: n={lat['count']} p50={1e3 * lat['p50']:.2f} ms "
                f"p95={1e3 * lat['p95']:.2f} ms p99={1e3 * lat['p99']:.2f} ms "
                f"max={1e3 * lat['max']:.2f} ms"
            )
        pend = fleet.get("pending_requeued")
        if pend is not None:
            lines.append(
                f"fleet: requeues={fleet.get('requeues', 0)} "
                f"failed_over={fleet.get('failed_over', 0)} "
                f"pending_requeued={pend}"
            )
    else:
        lines.append("")
        lines.append("no slo_snapshot yet (run the daemon with "
                     "--metrics-interval to emit them)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("metrics_jsonl",
                    help="the daemon's --metrics JSONL file")
    ap.add_argument(
        "--watch", type=float, default=0.0, metavar="SECONDS",
        help="re-render every SECONDS (0 = render once and exit)",
    )
    args = ap.parse_args(argv)
    while True:
        print(render(collect(args.metrics_jsonl)))
        if args.watch <= 0:
            return 0
        time.sleep(args.watch)
        print("\n" + "=" * 72 + "\n")


if __name__ == "__main__":
    sys.exit(main())
