"""Microbench: two-step pair composition of the reduced forward chain.

VERDICT r4 #4 — the declared remaining ceiling is the 2-component
sequential chains; the obvious lever is precomposing consecutive per-pair
2x2 step matrices so the serial recurrence takes half the steps.  This
script isolates the FORWARD kernel (the posterior/EM chain bound) and
A/Bs four lowerings on the real chip before any framework surgery:

  single      — the shipped _oh_fwd_kernel arithmetic: per-step in-kernel
                select tree over the 16-pair table (the r4 baseline).
  single-strm — same chain, but per-step matrices STREAMED from HBM
                (gathered outside) instead of selected in-kernel: isolates
                select-tree issue cost from chain latency (16 B/sym reads,
                fine per the r4 stats-kernel precedent).
  composed    — double-step chain: alpha_{t+1} = (alpha_{t-1} @ T2) /
                (alpha_{t-1} . R) with T2 = T_t @ T_{t+1} precomposed and
                R = rowsums(T_t); the intermediate alpha_t = (alpha_{t-1}
                @ T_t) / sum(alpha_{t-1}) hangs OFF the chain.  Streams T2
                + R + T_odd (20 B/sym).  Identical real arithmetic to the
                single-step chain (scalars cancel), f32 rounding differs.
  composed-sel— the same double-step chain with in-kernel selects over the
                100-row composed table (trip = pair_even * 5 + succ).

All variants write the same [Tp, 2, NL] alpha stream and are checked
allclose against the single-step XLA reference before timing.

Usage: python tools/bench_compose.py [--mib 64] [--platform auto]
"""

from __future__ import annotations

import argparse
import functools
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mib", type=int, default=64)
    ap.add_argument("--platform", default="auto")
    ap.add_argument("--lane-T", type=int, default=65536)
    ap.add_argument("--chain", type=int, default=8)
    args = ap.parse_args()

    import jax

    if args.platform != "auto":
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.ops import fb_onehot
    from cpgisland_tpu.ops.fb_pallas import _fb_lane_tile
    from cpgisland_tpu.ops.viterbi_onehot import (
        GROUP,
        ROW_TILE,
        _bcast_tab,
        _groups,
        _interpret,
        _vspec,
    )

    on_tpu = jax.default_backend() == "tpu"
    print(f"devices: {jax.devices()}", file=sys.stderr)

    params = presets.durbin_cpg8()
    S = params.n_symbols
    gt = _groups(params)
    tab = fb_onehot.prob_pair_table(params, gt)  # [16, 4]
    nreal = S * S

    # Off-TPU the kernels run under the Pallas interpreter: tiny smoke size
    # (correctness/tracing only — the timing answer is meaningful on TPU).
    T = (args.mib << 20) if on_tpu else (256 << 10)
    lane_T = args.lane_T if on_tpu else 2048
    if T % lane_T:
        raise SystemExit("size must divide lane_T")
    NL = T // lane_T
    # Production t-tile (fb_pallas.DEFAULT_T_TILE): bigger tiles OOM the
    # scoped VMEM on the [Tt, GROUP, lt] alpha out-spec.
    Tt = min(lane_T, 512)
    rng = np.random.default_rng(0)
    syms = rng.integers(0, S, size=T + 1, dtype=np.int32)
    pair2 = jnp.asarray(
        (syms[:-1] * S + syms[1:]).reshape(NL, lane_T).T
    )  # [lane_T, NL] all-real pairs
    lens2 = jnp.full((1, NL), lane_T, jnp.int32)
    a0 = rng.random((GROUP, NL)).astype(np.float32) + 0.1
    a0_red = jnp.asarray(a0)

    lt = _fb_lane_tile(NL)
    n_t = lane_T // Tt
    grid = (NL // lt, n_t)
    lane_spec = _vspec((1, lt), lambda i, j: (0, i))
    glane_spec = _vspec((GROUP, lt), lambda i, j: (0, i))
    step_spec = _vspec((Tt, lt), lambda i, j: (j, i))
    out_specs = [_vspec((Tt, GROUP, lt), lambda i, j: (j, 0, i))]
    out_shape = [jax.ShapeDtypeStruct((lane_T, GROUP, NL), jnp.float32)]
    scratch = [pltpu.VMEM((GROUP, lt), jnp.float32)]

    # --- reference (XLA scan twin of the single-step chain) ---------------
    def ref_alphas(pair2):
        tab_ext = jnp.concatenate(
            [tab, jnp.asarray([fb_onehot.PROB_IDENT], jnp.float32)], axis=0
        )
        return fb_onehot._xla_fwd_onehot(tab_ext, pair2, lens2, a0_red.T)

    # --- variant: single (shipped kernel) ---------------------------------
    def run_single(pair2):
        (alphas,) = pl.pallas_call(
            functools.partial(fb_onehot._oh_fwd_kernel, nreal=nreal, Tt=Tt),
            grid=grid,
            in_specs=[step_spec, lane_spec, glane_spec,
                      _vspec((nreal * 4, lt), lambda i, j: (0, 0))],
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=scratch,
            interpret=_interpret(),
        )(pair2, lens2, a0_red, _bcast_tab(tab, lt))
        return alphas

    # --- variant: single-strm (streamed per-step matrices) ----------------
    # Four [lane_T, NL] streams (one per matrix entry): keeps NL minor so
    # the HBM layout does not pad a tiny trailing dim 32x.
    def mat_stream(pair2):
        return tuple(tab[:, k][pair2] for k in range(4))

    def _fwd_strm_kernel(m00_ref, m01_ref, m10_ref, m11_ref, lens_ref,
                         a0_ref, alphas_ref, carry_ref, *, Tt):
        j = pl.program_id(1)
        lens = lens_ref[0, :]
        v0 = jnp.where(j == 0, a0_ref[0:1, :], carry_ref[0:1, :])
        v1 = jnp.where(j == 0, a0_ref[1:2, :], carry_ref[1:2, :])

        def body(tile_i, carry):
            v0, v1 = carry
            base = tile_i * ROW_TILE
            t00 = m00_ref[pl.ds(base, ROW_TILE), :]
            t01 = m01_ref[pl.ds(base, ROW_TILE), :]
            t10 = m10_ref[pl.ds(base, ROW_TILE), :]
            t11 = m11_ref[pl.ds(base, ROW_TILE), :]
            for r in range(ROW_TILE):
                t = j * Tt + base + r
                v_t = (t < lens)[None, :]
                inv = 1.0 / (v0 + v1)
                raw0 = v0 * t00[r : r + 1, :] + v1 * t10[r : r + 1, :]
                raw1 = v0 * t01[r : r + 1, :] + v1 * t11[r : r + 1, :]
                n0 = jnp.where(v_t, raw0 * inv, v0)
                n1 = jnp.where(v_t, raw1 * inv, v1)
                n0 = jnp.where(t == 0, a0_ref[0:1, :], n0)
                n1 = jnp.where(t == 0, a0_ref[1:2, :], n1)
                alphas_ref[base + r, :, :] = jnp.concatenate([n0, n1], axis=0)
                v0, v1 = n0, n1
            return v0, v1

        v0, v1 = jax.lax.fori_loop(0, Tt // ROW_TILE, body, (v0, v1))
        carry_ref[0:1, :] = v0
        carry_ref[1:2, :] = v1

    def run_single_strm(pair2):
        ms = mat_stream(pair2)
        (alphas,) = pl.pallas_call(
            functools.partial(_fwd_strm_kernel, Tt=Tt),
            grid=grid,
            in_specs=[step_spec] * 4 + [lane_spec, glane_spec],
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=scratch,
            interpret=_interpret(),
        )(*ms, lens2, a0_red)
        return alphas

    # --- variant: composed (streamed T2 / R / T_odd) ----------------------
    # Double step i covers steps (2i, 2i+1):
    #   inter   alpha_{2i}   = (v @ T_{2i}) / (v0 + v1)        [off-chain]
    #   carry   alpha_{2i+1} = (v @ T2_i) / (v . R_i)          [on-chain]
    # with T2_i = T_{2i} @ T_{2i+1}, R_i = rowsums(T_{2i}).
    ident4 = jnp.asarray([1.0, 0.0, 0.0, 1.0], jnp.float32)

    def composed_streams(pair2):
        # Per-entry [H, NL] streams (NL minor — no layout padding blowup).
        ge = [tab[:, k][pair2[0::2]] for k in range(4)]  # even steps
        go = [tab[:, k][pair2[1::2]] for k in range(4)]  # odd steps
        # Per-lane position 0 never applies its step matrix (the kernels
        # override alpha_0 = a0); bake that into the streams as an identity
        # EVEN half for double-step 0, so the composed step applies T_1 only.
        for k, idv in enumerate((1.0, 0.0, 0.0, 1.0)):
            ge[k] = ge[k].at[0].set(idv)
        t2 = (
            ge[0] * go[0] + ge[1] * go[2],
            ge[0] * go[1] + ge[1] * go[3],
            ge[2] * go[0] + ge[3] * go[2],
            ge[2] * go[1] + ge[3] * go[3],
        )
        rs = (ge[0] + ge[1], ge[2] + ge[3])
        return t2, rs, tuple(ge)

    def _fwd_comp_kernel(t200_ref, t201_ref, t210_ref, t211_ref,
                         r0_ref, r1_ref, te00_ref, te01_ref, te10_ref,
                         te11_ref, lens_ref, a0_ref,
                         alphas_ref, carry_ref, *, Tt):
        j = pl.program_id(1)
        lens = lens_ref[0, :]
        v0 = jnp.where(j == 0, a0_ref[0:1, :], carry_ref[0:1, :])
        v1 = jnp.where(j == 0, a0_ref[1:2, :], carry_ref[1:2, :])

        def body(tile_i, carry):
            # 16 symbols (8 double-steps) per body: 8-row-aligned H reads.
            v0, v1 = carry
            base = tile_i * 2 * ROW_TILE
            hb = tile_i * ROW_TILE
            T2 = [r[pl.ds(hb, ROW_TILE), :]
                  for r in (t200_ref, t201_ref, t210_ref, t211_ref)]
            R = [r[pl.ds(hb, ROW_TILE), :] for r in (r0_ref, r1_ref)]
            TE = [r[pl.ds(hb, ROW_TILE), :]
                  for r in (te00_ref, te01_ref, te10_ref, te11_ref)]
            for h in range(ROW_TILE):
                t = j * Tt + base + 2 * h
                act0 = (t < lens)[None, :]
                act1 = (t + 1 < lens)[None, :]
                # Off-chain intermediate (single even step).
                inv = 1.0 / (v0 + v1)
                w0 = v0 * TE[0][h : h + 1, :] + v1 * TE[2][h : h + 1, :]
                w1 = v0 * TE[1][h : h + 1, :] + v1 * TE[3][h : h + 1, :]
                i0 = jnp.where(act0, w0 * inv, v0)
                i1 = jnp.where(act0, w1 * inv, v1)
                i0 = jnp.where(t == 0, a0_ref[0:1, :], i0)
                i1 = jnp.where(t == 0, a0_ref[1:2, :], i1)
                # On-chain composed step.
                den = v0 * R[0][h : h + 1, :] + v1 * R[1][h : h + 1, :]
                dinv = 1.0 / den
                u0 = v0 * T2[0][h : h + 1, :] + v1 * T2[2][h : h + 1, :]
                u1 = v0 * T2[1][h : h + 1, :] + v1 * T2[3][h : h + 1, :]
                n0 = jnp.where(act1, u0 * dinv, i0)
                n1 = jnp.where(act1, u1 * dinv, i1)
                alphas_ref[base + 2 * h, :, :] = jnp.concatenate([i0, i1], axis=0)
                alphas_ref[base + 2 * h + 1, :, :] = jnp.concatenate([n0, n1], axis=0)
                v0, v1 = n0, n1
            return v0, v1

        v0, v1 = jax.lax.fori_loop(0, Tt // (2 * ROW_TILE), body, (v0, v1))
        carry_ref[0:1, :] = v0
        carry_ref[1:2, :] = v1

    def run_composed(pair2):
        t2, rs, te = composed_streams(pair2)
        half_spec = _vspec((Tt // 2, lt), lambda i, j: (j, i))
        (alphas,) = pl.pallas_call(
            functools.partial(_fwd_comp_kernel, Tt=Tt),
            grid=grid,
            in_specs=[half_spec] * 10 + [lane_spec, glane_spec],
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=scratch,
            interpret=_interpret(),
        )(*t2, *rs, *te, lens2, a0_red)
        return alphas

    # --- variant: composed-sel (in-kernel select over composed tables) ----
    # trip = pair_even * (S+1) + (pair_odd % S); row S*S*(S+1) + p encodes
    # an identity even half with odd pair p (double-step 0 of each lane);
    # paire index S*S = identity row of the rowsum / even tables.
    def comp_tables():
        tab_np = np.asarray(tab).reshape(S * S, 2, 2)
        rows = []
        for p in range(S * S):
            e = p % S
            for q in range(S + 1):
                m = tab_np[p] @ tab_np[e * S + q] if q < S else tab_np[p]
                rows.append(m.reshape(4))
        t2tab = jnp.asarray(
            np.concatenate([np.stack(rows), tab_np.reshape(S * S, 4)])
        )  # [16*5 + 16, 4]
        rtab = jnp.asarray(
            np.concatenate([tab_np.sum(axis=2), np.ones((1, 2), np.float32)])
        )  # [17, 2]
        ttab = jnp.concatenate([tab, ident4[None, :]], axis=0)  # [17, 4]
        return t2tab, rtab, ttab

    N_TRIP = S * S * (S + 1) + S * S
    N_PE = S * S + 1

    def _sel_rows(tile, tab_ref, n, width):
        outs = [jnp.zeros(tile.shape, jnp.float32) for _ in range(width)]
        for p in range(n):
            cmp = tile == p
            for k in range(width):
                outs[k] = jnp.where(
                    cmp, tab_ref[width * p + k : width * p + k + 1, :], outs[k]
                )
        return outs

    def _fwd_compsel_kernel(trip_ref, paire_ref, lens_ref, a0_ref, t2tab_ref,
                            rtab_ref, ttab_ref, alphas_ref, carry_ref, *, Tt):
        j = pl.program_id(1)
        lens = lens_ref[0, :]
        v0 = jnp.where(j == 0, a0_ref[0:1, :], carry_ref[0:1, :])
        v1 = jnp.where(j == 0, a0_ref[1:2, :], carry_ref[1:2, :])

        def body(tile_i, carry):
            # 16 symbols (= 8 double-steps) per body so the trip/paire tile
            # reads stay 8-row-aligned (the Mosaic constraint).
            v0, v1 = carry
            base = tile_i * 2 * ROW_TILE
            hb = tile_i * ROW_TILE
            trip = trip_ref[pl.ds(hb, ROW_TILE), :]
            pe = paire_ref[pl.ds(hb, ROW_TILE), :]
            T2 = _sel_rows(trip, t2tab_ref, N_TRIP, 4)
            R = _sel_rows(pe, rtab_ref, N_PE, 2)
            TE = _sel_rows(pe, ttab_ref, N_PE, 4)
            for h in range(ROW_TILE):
                t = j * Tt + base + 2 * h
                act0 = (t < lens)[None, :]
                act1 = (t + 1 < lens)[None, :]
                inv = 1.0 / (v0 + v1)
                w0 = v0 * TE[0][h : h + 1, :] + v1 * TE[2][h : h + 1, :]
                w1 = v0 * TE[1][h : h + 1, :] + v1 * TE[3][h : h + 1, :]
                i0 = jnp.where(act0, w0 * inv, v0)
                i1 = jnp.where(act0, w1 * inv, v1)
                i0 = jnp.where(t == 0, a0_ref[0:1, :], i0)
                i1 = jnp.where(t == 0, a0_ref[1:2, :], i1)
                den = v0 * R[0][h : h + 1, :] + v1 * R[1][h : h + 1, :]
                dinv = 1.0 / den
                u0 = v0 * T2[0][h : h + 1, :] + v1 * T2[2][h : h + 1, :]
                u1 = v0 * T2[1][h : h + 1, :] + v1 * T2[3][h : h + 1, :]
                n0 = jnp.where(act1, u0 * dinv, i0)
                n1 = jnp.where(act1, u1 * dinv, i1)
                alphas_ref[base + 2 * h, :, :] = jnp.concatenate([i0, i1], axis=0)
                alphas_ref[base + 2 * h + 1, :, :] = jnp.concatenate([n0, n1], axis=0)
                v0, v1 = n0, n1
            return v0, v1

        v0, v1 = jax.lax.fori_loop(0, Tt // (2 * ROW_TILE), body, (v0, v1))
        carry_ref[0:1, :] = v0
        carry_ref[1:2, :] = v1

    def run_composed_sel(pair2):
        t2tab, rtab, ttab = comp_tables()
        trip = pair2[0::2] * (S + 1) + pair2[1::2] % S  # [H, NL]
        paire = pair2[0::2]
        # Double-step 0 of each lane: identity even half (alpha_0 is the
        # override; only T_1 applies).
        trip = trip.at[0].set(S * S * (S + 1) + pair2[1])
        paire = paire.at[0].set(S * S)
        (alphas,) = pl.pallas_call(
            functools.partial(_fwd_compsel_kernel, Tt=Tt),
            grid=grid,
            in_specs=[
                _vspec((Tt // 2, lt), lambda i, j: (j, i)),
                _vspec((Tt // 2, lt), lambda i, j: (j, i)),
                lane_spec, glane_spec,
                _vspec((N_TRIP * 4, lt), lambda i, j: (0, 0)),
                _vspec((N_PE * 2, lt), lambda i, j: (0, 0)),
                _vspec((N_PE * 4, lt), lambda i, j: (0, 0)),
            ],
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=scratch,
            interpret=_interpret(),
        )(trip, paire, lens2, a0_red, _bcast_tab(t2tab, lt),
          _bcast_tab(rtab, lt), _bcast_tab(ttab, lt))
        return alphas

    variants = {
        "single": run_single,
        "single-strm": run_single_strm,
        "composed": run_composed,
        "composed-sel": run_composed_sel,
    }

    # --- correctness gate (small slice; scalar fetched — the relay chokes
    # on multi-hundred-MiB array fetches) then chained timing --------------
    NGATE = min(NL, 2 * lt)
    pair_g = pair2[:, :NGATE]
    lens_g = lens2[:, :NGATE]
    a0_g = a0_red[:, :NGATE]
    saved = (pair2, lens2, a0_red, NL, grid, out_shape)
    pair2, lens2, a0_red, NL = pair_g, lens_g, a0_g, NGATE
    grid = (NGATE // lt, n_t)
    out_shape = [jax.ShapeDtypeStruct((lane_T, GROUP, NGATE), jnp.float32)]

    @jax.jit
    def gate_err(fn_out, pair_g):
        ref = ref_alphas(pair_g)
        return jnp.max(jnp.abs(fn_out - ref) / jnp.maximum(jnp.abs(ref), 1e-3))

    for name, fn in variants.items():
        if not on_tpu and name == "single":
            continue  # interpreter: pathologically slow select chains
        print(f"gating {name}...", file=sys.stderr)
        err = float(gate_err(jax.jit(fn)(pair_g), pair_g))
        print(f"{name}: max rel err vs XLA ref = {err:.2e}", file=sys.stderr)
        assert err < 1e-4, f"{name} WRONG (err {err:.2e})"
    pair2, lens2, a0_red, NL, grid, out_shape = saved

    def timed(fn, name):
        print(f"timing {name}...", file=sys.stderr)

        @jax.jit
        def chained(c, pair2):
            def step(c, _):
                al = fn(pair2.at[0, 0].set(c % (S * S)))
                return (jnp.sum(al[-1]) * 1e3).astype(jnp.int32) % 7, None

            c, _ = jax.lax.scan(step, c, None, length=args.chain)
            return c

        jax.block_until_ready(chained(jnp.int32(0), pair2))
        best = float("inf")
        for s in range(1, 4):
            t0 = time.perf_counter()
            int(jax.device_get(chained(jnp.int32(s), pair2)))
            dt = (time.perf_counter() - t0) / args.chain
            if dt > 1e-4:
                best = min(best, dt)
        if not np.isfinite(best):
            raise RuntimeError(f"{name}: all reps phantom (~0 ms) — no measurement")
        print(f"{name}: {T / best / 1e6:.1f} Msym/s ({best*1e3:.1f} ms)",
              file=sys.stderr)
        return T / best

    results = {}
    for name, fn in variants.items():
        if not on_tpu and name == "single":
            continue
        results[name] = timed(fn, name)
    import json

    print(json.dumps({k: round(v / 1e6, 1) for k, v in results.items()}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
