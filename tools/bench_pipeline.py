"""A/B the streaming pipeline: serial vs overlapped, SAME user path.

Measures `pipeline.decode_file` and `pipeline.posterior_file` end to end
(host FASTA parse -> encode -> upload -> compute -> island calls) on one
generated multi-record FASTA, with `prefetch=0` (the strictly serial
cadence) against `prefetch=N` (double-buffered streaming: background-thread
encode, span-upload overlap, deferred call-column fetch).

Methodology per BASELINE.md: both arms run the IDENTICAL user path and pay
the same per-byte host encode and upload — the published figure is the
RATIO between the two walls, never an upload-subtracted "net" (the upload
baseline alone is too noisy on the relay).  Each arm runs ``--reps`` times
taking the best wall; island outputs are asserted identical between arms
(the overlap must change timing only).  The run emits one JSON line on
stdout; progress and per-arm walls go to stderr.

Expect ~1.0x on CPU: there the "device" compute IS host compute, so there
is no disjoint resource to hide the encode behind — the harness exists for
TPU captures (relay RTT + single-digit-MB/s upload + real device compute),
where the serial cadence leaves the chip idle during every encode/upload.

Usage:
  python tools/bench_pipeline.py [--platform auto] [--mbases 8]
                                 [--records 32] [--prefetch 4] [--reps 3]
"""

from __future__ import annotations

import argparse
import io
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _write_fasta(path: str, n_records: int, total_syms: int, seed: int) -> None:
    """Multi-record FASTA with planted CpG islands: record sizes spread
    across pow2 classes so both the batched small-record path and the
    sharded large-record path run (the shapes real assemblies have)."""
    rng = np.random.default_rng(seed)
    # Geometric-ish size spread, one dominant record (the "chromosome").
    weights = np.array([2.0 ** (i % 5) for i in range(n_records)])
    weights[0] = weights.sum() * 2
    sizes = np.maximum(1024, (total_syms * weights / weights.sum()).astype(int))
    bases = np.array(list("acgt"))
    with open(path, "w") as f:
        for r, n in enumerate(sizes):
            f.write(f">rec{r}\n")
            bg = rng.choice(4, size=n, p=[0.3, 0.2, 0.2, 0.3])
            # Plant islands (CG-rich stretches) every ~16 Ki.
            for lo in range(0, n - 2048, 1 << 14):
                bg[lo : lo + 1024] = rng.choice(4, size=1024, p=[0.08, 0.42, 0.42, 0.08])
            s = "".join(bases[bg])
            for i in range(0, len(s), 120):
                f.write(s[i : i + 120] + "\n")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="auto")
    ap.add_argument("--engine", default="auto")
    ap.add_argument("--island-engine", default="auto")
    ap.add_argument("--mbases", type=int, default=None,
                    help="total FASTA size (default 32 on TPU, 4 on CPU)")
    ap.add_argument("--records", type=int, default=32)
    ap.add_argument("--prefetch", type=int, default=4)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--span", type=int, default=None,
                    help="decode/posterior span override (forces multi-span "
                    "records to exercise the upload overlap)")
    args = ap.parse_args()

    import jax

    if args.platform != "auto":
        jax.config.update("jax_platforms", args.platform)
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.models import presets

    on_tpu = jax.default_backend() == "tpu"
    _log(f"devices: {jax.devices()}")
    mbases = args.mbases if args.mbases else (32 if on_tpu else 4)
    params = presets.durbin_cpg8()

    tdir = tempfile.mkdtemp(prefix="bench_pipeline_")
    fa = os.path.join(tdir, "g.fa")
    _write_fasta(fa, args.records, mbases << 20, seed=5)
    _log(f"fasta: {args.records} records, ~{mbases} Mbases -> {fa}")

    span = args.span if args.span else (
        pipeline.CLEAN_DECODE_SPAN if on_tpu else (2 << 20)
    )
    pspan = args.span if args.span else (
        pipeline.POSTERIOR_SPAN if on_tpu else (2 << 20)
    )

    def run_decode(prefetch: int) -> tuple:
        out = io.StringIO()
        t0 = time.perf_counter()
        res = pipeline.decode_file(
            fa, params, islands_out=out, compat=False, span=span,
            engine=args.engine, island_engine=args.island_engine,
            prefetch=prefetch,
        )
        return time.perf_counter() - t0, out.getvalue(), res.n_symbols

    def run_posterior(prefetch: int) -> tuple:
        out = io.StringIO()
        t0 = time.perf_counter()
        res = pipeline.posterior_file(
            fa, params, islands_out=out, span=pspan, engine=args.engine,
            island_engine=args.island_engine, prefetch=prefetch,
        )
        return time.perf_counter() - t0, out.getvalue(), res.n_symbols

    results: dict = {"mbases": mbases, "records": args.records,
                     "prefetch": args.prefetch}
    for name, fn in (("decode", run_decode), ("posterior", run_posterior)):
        walls = {}
        outputs = {}
        # Warm the compile caches OUTSIDE the timed arms: the first arm
        # would otherwise eat every XLA compile and the "speedup" would be
        # mostly cache warmth, not overlap.
        fn(0)
        for arm, depth in (("serial", 0), ("overlapped", args.prefetch)):
            best = float("inf")
            for rep in range(args.reps):
                wall, text, n_sym = fn(depth)
                best = min(best, wall)
                _log(f"{name}/{arm} rep{rep}: {wall:.3f} s "
                     f"({n_sym / wall / 1e6:.1f} Msym/s end-to-end)")
            walls[arm] = best
            outputs[arm] = text
        if outputs["serial"] != outputs["overlapped"]:
            raise AssertionError(
                f"{name}: overlapped island output differs from serial — "
                "the overlap must be timing-only"
            )
        ratio = walls["serial"] / walls["overlapped"]
        results[name] = {
            "serial_s": round(walls["serial"], 3),
            "overlapped_s": round(walls["overlapped"], 3),
            "overlap_speedup": round(ratio, 3),
            "islands": outputs["serial"].count("\n"),
            "outputs_identical": True,
        }
        _log(f"{name}: serial {walls['serial']:.3f} s, overlapped "
             f"{walls['overlapped']:.3f} s -> {ratio:.2f}x (same user path, "
             f"same per-byte upload; outputs identical)")

    print(json.dumps(results))
    return 0


if __name__ == "__main__":
    sys.exit(main())
