"""graftune CLI — the knob-autotuner sweep driver (ROADMAP item 1).

One command replaces the three hand-driven chip-window harnesses: the
lane/t_tile/block sweeps, tools/bench_passfusion.py's per-path fused
A/B decisions, and tools/bench_multimodel.py's per-site stacked
decisions all run as tune tasks — feasibility-pruned through graftmem
BEFORE any compile, parity-gated against the current default arm BEFORE
any timing, timed with the full bench relay discipline, and persisted
into the fingerprint-keyed TUNING.json winner table the routers consult.

Usage:
  python tools/graftune.py --all                      # TPU capture window
  python tools/graftune.py --all --update-tune --apply    # ... and persist
  python tools/graftune.py --kernel lane              # task-name prefix
  python tools/graftune.py --platform cpu --smoke     # CI slice (one task
        # per kernel family/engine: reduced FB, stacked, flat decode)

Persistence flags (without them the sweep only reports):
  --update-tune   write the geometry-knob winner rows (lane/t_tile/
                  block/engine) into TUNING.json
  --apply         write the fused/stacked verdict rows (keep-or-flip; the
                  BASELINE.md decision rule runs in code — flips apply
                  only on the capturing TPU past the margin, CPU sweeps
                  record projections and keep the shipped defaults)

Stdout is ONE JSON line (the report incl. per-task verdict blocks and
the prune/compile ledger); progress goes to stderr.  Exit 1 when any
task failed or a pruned tuple reached compile (ledger-asserted).
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="auto",
                    help="cpu | tpu | auto (whatever jax picks)")
    ap.add_argument("--all", action="store_true", dest="run_all",
                    help="run every tune task")
    ap.add_argument("--kernel", default=None,
                    help="task-name prefix filter (e.g. lane, fused, "
                    "flat.block, stacked)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU sizes, the one-task-per-kernel-family "
                    "slice (CI; rates are projections)")
    ap.add_argument("--update-tune", action="store_true",
                    help="persist geometry-knob winners to TUNING.json")
    ap.add_argument("--apply", action="store_true",
                    help="persist fused/stacked verdict rows to TUNING.json")
    ap.add_argument("--tune-file", default=None,
                    help="winner-table path (default: <repo>/TUNING.json)")
    ap.add_argument("--mib", type=int, default=None,
                    help="symbols (MiB) per timed input (default: 64 on "
                    "TPU, 2 on CPU, 0.25 under --smoke)")
    ap.add_argument("--chain", type=int, default=None,
                    help="data-dependent reps inside one lax.scan")
    ap.add_argument("--reps", type=int, default=None,
                    help="wall repetitions per arm (min taken)")
    ap.add_argument("--members", type=int, default=3,
                    help="stacked-arm member count")
    ap.add_argument("--list", action="store_true", dest="list_tasks",
                    help="list tune tasks and exit (no backend)")
    args = ap.parse_args()

    from cpgisland_tpu.tune import tasks as tune_tasks

    if args.list_tasks:
        for t in tune_tasks.all_tasks():
            smoke = " [smoke]" if t.name in tune_tasks.SMOKE_TASKS else ""
            print(f"{t.name}  ({t.family}; costs: "
                  f"{', '.join(t.costs_entries)}){smoke}")
        return 0

    if not (args.run_all or args.kernel or args.smoke):
        ap.error("pick --all, --kernel PREFIX, or --smoke")

    import jax

    if args.platform != "auto":
        # Pin via jax.config BEFORE backend init: this dev box's site
        # plugin ignores the JAX_PLATFORMS env var (CLAUDE.md).
        jax.config.update("jax_platforms", args.platform)

    from cpgisland_tpu.tune import sweep, table

    if args.tune_file:
        table.set_table_path(args.tune_file)

    on_tpu = jax.default_backend() == "tpu"
    if args.smoke:
        n = (args.mib << 20) if args.mib else (256 << 10)
        chain, reps = args.chain or 2, args.reps or 1
    elif on_tpu:
        n = (args.mib or 64) << 20
        chain, reps = args.chain or 6, args.reps or 3
    else:
        # CPU projection sizes: the machinery cycle is real, the rates are
        # not the chip answer (winners stay recorded-not-applied for
        # geometry knobs; verdicts keep the shipped defaults).
        n = (args.mib or 2) << 20
        chain, reps = args.chain or 2, args.reps or 2
    cfg = tune_tasks.SweepConfig(
        n=n, chain=chain, reps=reps, members=args.members,
        smoke=args.smoke,
    )
    names = list(tune_tasks.SMOKE_TASKS) if args.smoke else None
    if not tune_tasks.tasks_by_name(names, args.kernel):
        ap.error(
            f"no tune task matches --kernel {args.kernel!r}"
            + (" within the --smoke slice "
               f"{list(tune_tasks.SMOKE_TASKS)} (drop --smoke to reach "
               "the full registry)" if args.smoke else
               " (see --list)")
        )
    report = sweep.run_sweep(
        names=names, prefix=args.kernel, cfg=cfg, smoke=args.smoke,
        log=log,
    )
    path = None
    if args.update_tune or args.apply:
        path = sweep.persist(
            report, update_tune=args.update_tune,
            apply_verdicts=args.apply, path=args.tune_file,
        )
        if path:
            log(f"graftune: winners persisted to {path}")
    report.pop("_reports", None)
    report["persisted"] = path
    report["table"] = table.table_report(path=args.tune_file)
    print(json.dumps(report))
    return 0 if report["ledger"]["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
