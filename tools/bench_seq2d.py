"""Diagnose the em-seq2d gap (VERDICT r4 #3): 725 vs 989.6 Msym/s/iter.

Measures each bucket group of the bench's seq2d config SEPARATELY (the
32 Mi chromosome group and the 8 x 2 Mi scaffold group), plus lane_T /
t_tile sweeps per group, so the composite gap decomposes into per-group
causes before any code changes.

Usage: python tools/bench_seq2d.py [--platform auto]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="auto")
    ap.add_argument("--chain", type=int, default=8)
    ap.add_argument("--scale", type=float, default=None)
    args = ap.parse_args()

    import jax

    if args.platform != "auto":
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.train.backends import Seq2DBackend
    from cpgisland_tpu.train.baum_welch import mstep
    from cpgisland_tpu.utils import chunking

    on_tpu = jax.default_backend() == "tpu"
    scale = args.scale if args.scale is not None else (1.0 if on_tpu else 1 / 32)
    print(f"devices: {jax.devices()}", file=sys.stderr)
    params = presets.durbin_cpg8()
    rng = np.random.default_rng(8)
    groups = [(1, int((32 << 20) * scale)), (8, int((2 << 20) * scale))]

    def timed_group(rows, ln, engine, lane_T, t_tile, chain):
        backend = Seq2DBackend(engine=engine, lane_T=lane_T, t_tile=t_tile)
        chunks = rng.integers(0, 4, size=(rows, ln), dtype=np.int32).astype(np.uint8)
        lens = np.full(rows, ln, np.int32)
        bucketed = chunking.Bucketed(
            chunks=(chunks,), lengths=(lens,), total=rows * ln
        )
        prepared = backend.prepare(bucketed)
        obs_t, len_t = backend.place(prepared.chunks, prepared.lengths)
        mesh_g, obs, lens_p = backend._group_meshes[0], obs_t[0], len_t[0]

        @jax.jit
        def chained(p, obs, lens, s):
            obs = obs.at[0, 0].set((s % 4).astype(obs.dtype))

            def body(p, _):
                return mstep(p, backend._group_stats(p, mesh_g, obs, lens)), None

            p, _ = jax.lax.scan(body, p, None, length=chain)
            return p

        jax.block_until_ready(chained(params, obs, lens_p, jnp.int32(0)))
        best = float("inf")
        s, done, phantoms = 1, 0, 0
        while done < 3:
            t0 = time.perf_counter()
            float(
                np.asarray(
                    jax.device_get(chained(params, obs, lens_p, jnp.int32(s)).log_pi)
                ).sum()
            )
            dt = time.perf_counter() - t0
            s += 1
            if dt < 1e-4:
                phantoms += 1
                if phantoms > 4:
                    raise RuntimeError("persistent phantom ~0 ms timings")
                continue
            best = min(best, dt)
            done += 1
        return rows * ln / (best / chain)

    eng = "onehot" if on_tpu else "xla"
    results = {}
    for rows, ln in groups:
        name = f"{rows}x{ln >> 20}MiB"
        r = timed_group(rows, ln, eng, None, None, args.chain)
        results[f"{name}-default"] = round(r / 1e6, 1)
        print(f"{name} default: {r/1e6:.1f} Msym/s", file=sys.stderr)
        if on_tpu:
            for lt in (16384, 32768, 65536):
                if lt > ln:
                    continue
                r = timed_group(rows, ln, eng, lt, None, args.chain)
                results[f"{name}-lt{lt}"] = round(r / 1e6, 1)
                print(f"{name} lane_T={lt}: {r/1e6:.1f} Msym/s", file=sys.stderr)

    # Composite (the bench's metric shape): time-weighted over both groups.
    tot = sum(r * ln for r, ln in groups)
    t = sum(
        (r * ln) / (results[f"{r}x{ln >> 20}MiB-default"] * 1e6)
        for r, ln in groups
    )
    results["composite-default"] = round(tot / t / 1e6, 1)
    print(f"composite default: {tot / t / 1e6:.1f} Msym/s", file=sys.stderr)
    print(json.dumps(results))
    return 0


if __name__ == "__main__":
    sys.exit(main())
