"""Diagnose the batched-decode gap (VERDICT r4 #2): 923 vs 1987.6 Msym/s.

Sweeps the knobs that differ between the batched (16 x 4 MiB vmap) and
single-stream (1 x 256 MiB) configs, on the same total symbol count:

  - block_size: the batched path inherits DEFAULT_BLOCK=4096; per record
    that is 1024 blocks whose [K,K] stitching scans are vmapped 16x.
  - batch geometry: 16 x 4 MiB vs 4 x 16 MiB vs 64 x 1 MiB at fixed total.
  - single-stream reference at the same 64 MiB total.

Prints Msym/s per config (chained timing, distinct seeds, fetch-per-rep —
the bench.py phantom defenses).

Usage: python tools/bench_batched.py [--platform auto] [--engine onehot]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="auto")
    ap.add_argument("--engine", default="auto")
    ap.add_argument("--chain", type=int, default=6)
    args = ap.parse_args()

    import jax

    if args.platform != "auto":
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.ops.viterbi_parallel import (
        viterbi_parallel,
        viterbi_parallel_batch,
    )
    from cpgisland_tpu.parallel.decode import resolve_engine

    on_tpu = jax.default_backend() == "tpu"
    print(f"devices: {jax.devices()}", file=sys.stderr)
    params = presets.durbin_cpg8()
    eng = resolve_engine(args.engine, params)
    total = (64 << 20) if on_tpu else (2 << 20)
    rng = np.random.default_rng(2)
    stream = rng.integers(0, 4, size=total, dtype=np.int32)

    def timed(fn, arg, n_sym, name, chain):
        @jax.jit
        def chained(c, x):
            def body(c, _):
                out = fn(x, c)
                return jnp.min(out).astype(jnp.int32), None

            c, _ = jax.lax.scan(body, c, None, length=chain)
            return c

        jax.block_until_ready(chained(jnp.int32(0), arg))
        best = float("inf")
        s, done, phantoms = 1, 0, 0
        while done < 3:
            t0 = time.perf_counter()
            int(jax.device_get(chained(jnp.int32(s), arg)))
            dt = time.perf_counter() - t0
            s += 1
            if dt < 1e-4:
                phantoms += 1
                if phantoms > 4:
                    raise RuntimeError("persistent phantom timings")
                continue
            best = min(best, dt)
            done += 1
        best /= chain
        rate = n_sym / best
        print(f"{name}: {rate/1e6:.1f} Msym/s ({best*1e3:.1f} ms)", file=sys.stderr)
        return rate / 1e6

    results = {}

    # Single-stream reference at the same total.
    def single(x, c):
        return viterbi_parallel(
            params, x.at[0].set(c % 4), return_score=False, engine=eng
        )

    results["single-64MiB"] = timed(
        single, jnp.asarray(stream), total, "single-64MiB", args.chain
    )

    # Batched geometries x block sizes.
    geoms = [(16, total // 16), (4, total // 4), (64, total // 64)]
    blocks = [4096, 8192, 16384, 32768] if on_tpu else [4096, 16384]
    for n_seqs, seq_len in geoms:
        chunks = jnp.asarray(stream.reshape(n_seqs, seq_len))
        lengths = jnp.full(n_seqs, seq_len, dtype=jnp.int32)
        for bk in blocks:
            if bk * 2 > seq_len:
                continue

            def batched(x, c, bk=bk, lengths=lengths):
                return viterbi_parallel_batch(
                    params, x.at[0, 0].set(c % 4), lengths,
                    block_size=bk, return_score=False, engine=eng,
                )

            name = f"batch{n_seqs}x{seq_len >> 20}MiB-bk{bk}"
            try:
                results[name] = timed(batched, chunks, total, name, args.chain)
            except Exception as e:
                results[name] = f"FAIL: {str(e)[:120]}"
                print(f"{name}: FAILED ({str(e)[:200]})", file=sys.stderr)

    print(json.dumps(results))
    return 0


if __name__ == "__main__":
    sys.exit(main())
