#!/usr/bin/env python
"""Client for the `cpgisland serve` daemon: FASTA in, island calls out.

Reads FASTA records, submits each as a JSONL request (decode by default,
--posterior for soft decoding), and writes the returned island calls in
the reference's `beg end len gc oe` line format (with a record-name
column, like the batch CLI's multi-record output).

Transport: --connect ENDPOINT (repeatable; an AF_UNIX path or a
`tcp:HOST:PORT` spec — `--socket PATH` stays as the single-endpoint
alias) connects to a running daemon; without either, the client SPAWNS
`python -m cpgisland_tpu serve` as a subprocess and talks over its
stdin/stdout — the zero-setup smoke path.

## Reconnect-with-replay (socket mode)

On socket death the client reconnects (up to --reconnects times, with
backoff) and re-submits exactly its INCOMPLETE ids.  With several
--connect endpoints the client ROTATES to the next on every connection
failure — the router-tier failover story: when one host (or the routing
front's unix door) dies, the alternates keep serving, and the journal
arbitration below makes the re-submission safe wherever it lands.  The
reconnect backoff honors the daemon's last load-shed hint: a rejection's
``retry_after_s`` is remembered and the next reconnect wait is at least
that long (shed clients must not stampede a saturated pod).  This is
safe against every daemon state because the daemon side already
arbitrates:

- an id still EXECUTING (or queued) is rejected with a duplicate-id error
  — the client backs off and retries it later (duplicate-id rejection of
  executing requests protects the daemon from double work);
- a `Backpressure` rejection carries a queue-depth-derived
  ``retry_after_s`` hint — the client sleeps that long instead of
  hot-looping on a saturated fleet;
- with the daemon's admission journal (`--manifest`), a re-submitted id
  whose first life COMPLETED replays bit-identically from the manifest
  (zero device work), and one that was admitted-but-incomplete at a crash
  is re-executed by the restarted daemon itself — the client's re-submit
  then simply waits out the duplicate rejection until the journal replay
  is ready.  No accepted request is ever served twice or dropped.

Examples:

    # one-shot: spawn a daemon, decode a file through it
    python tools/serve_client.py genome.fa --islands-out i.txt --platform cpu

    # against a running daemon
    python -m cpgisland_tpu serve --socket /tmp/cpg.sock &
    python tools/serve_client.py genome.fa --socket /tmp/cpg.sock \
        --islands-out i.txt --shutdown
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# A rejection whose error matches one of these is RETRYABLE-LATER: the id
# is alive on the daemon side (queued/executing/just-restarted) and will
# become replayable or reusable — never a hard failure.
_RETRY_MARKERS = ("already queued", "already in flight", "duplicate request id")
_DEFAULT_RETRY_S = 0.25


def iter_fasta_text(path: str):
    """(name, sequence-text) per FASTA record — text only, no encoding:
    the DAEMON encodes on its transport thread (that is the overlap)."""
    name, parts = None, []
    seen_any = False
    with open(path) as f:
        for line in f:
            if line.startswith(">"):
                if seen_any:
                    yield name or "", "".join(parts)
                name = line[1:].strip().split()[0] if line[1:].strip() else ""
                parts = []
                seen_any = True
            else:
                s = line.strip()
                if s:
                    parts.append(s)
                    seen_any = True
    if seen_any:
        yield name or "", "".join(parts)


def _connect(endpoint: str):
    """Connect one endpoint: a `tcp:HOST:PORT` spec or an AF_UNIX path."""
    if endpoint.startswith("tcp:"):
        host, port = endpoint[4:].rsplit(":", 1)
        conn = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        conn.connect((host, int(port)))
        return conn
    conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    conn.connect(endpoint)
    return conn


def run_socket_session(
    endpoints,
    requests: list,
    *,
    reconnects: int = 3,
    reconnect_wait_s: float = 0.5,
    max_id_retries: int = 40,
    log=None,
) -> dict:
    """Submit ``requests`` (JSON dicts with unique ``id``) over the daemon
    socket with reconnect-with-replay (see module docstring); returns
    {id: final response dict}.  ``endpoints`` is one endpoint or a list —
    each connection failure rotates to the next (alternate-endpoint
    failover against a routing tier).  Raises OSError once the reconnect
    budget is exhausted with ids still incomplete.  Each id's retryable
    rejections (duplicate-id / backpressure) are bounded by
    ``max_id_retries`` — past it the last rejection becomes the final
    response instead of spinning forever (e.g. against a colliding id
    from another client that never completes).  Reconnect waits honor the
    daemon's last ``retry_after_s`` load-shed hint."""
    log = log if log is not None else (lambda msg: None)
    if isinstance(endpoints, str):
        endpoints = [endpoints]
    endpoints = list(endpoints)
    ep_i = 0
    pending = {int(r["id"]): r for r in requests}
    responses: dict = {}
    attempts = 0
    id_retries: dict = {}
    last_hint = [0.0]  # most recent retry_after_s seen from the daemon

    def _reconnect_sleep() -> None:
        # Load-shed contract: never reconnect faster than the daemon's
        # last machine-readable hint asked us to.
        wait = max(reconnect_wait_s * attempts, last_hint[0])
        last_hint[0] = 0.0
        time.sleep(wait)

    while pending:
        retry_at: dict = {}  # id -> monotonic time of next re-submit
        endpoint = endpoints[ep_i % len(endpoints)]
        try:
            conn = _connect(endpoint)
        except OSError:
            attempts += 1
            ep_i += 1  # rotate: try the next endpoint first
            if attempts > reconnects:
                raise
            log(f"# serve_client: connect to {endpoint} failed; retrying "
                f"on {endpoints[ep_i % len(endpoints)]} "
                f"({attempts}/{reconnects})\n")
            _reconnect_sleep()
            continue
        try:
            wf = conn.makefile("w", encoding="utf-8")
            rf = conn.makefile("r", encoding="utf-8")
            outstanding: set = set()
            for rid, req in sorted(pending.items()):
                wf.write(json.dumps(req) + "\n")
                outstanding.add(rid)
            wf.flush()
            while outstanding or retry_at:
                # Re-submit ids whose backoff elapsed (duplicate-id /
                # backpressure rejections) on THIS connection.
                now = time.monotonic()
                due = [rid for rid, t in retry_at.items() if t <= now]
                if not outstanding and retry_at and not due:
                    time.sleep(min(retry_at.values()) - now)
                    due = [rid for rid, t in retry_at.items()
                           if t <= time.monotonic()]
                for rid in due:
                    del retry_at[rid]
                    wf.write(json.dumps(pending[rid]) + "\n")
                    outstanding.add(rid)
                if due:
                    wf.flush()
                line = rf.readline()
                if not line:
                    raise OSError("daemon closed the connection")
                resp = json.loads(line)
                rid = resp.get("id")
                if rid not in outstanding:
                    continue  # stats line / stale duplicate
                if resp.get("ok"):
                    outstanding.discard(rid)
                    responses[rid] = resp
                    del pending[rid]
                    continue
                err = str(resp.get("error", ""))
                retryable = (
                    resp.get("backpressure")
                    or any(m in err for m in _RETRY_MARKERS)
                )
                if retryable:
                    outstanding.discard(rid)
                    id_retries[rid] = id_retries.get(rid, 0) + 1
                    if id_retries[rid] > max_id_retries:
                        log(f"# serve_client: request {rid} still "
                            f"rejected after {max_id_retries} retries; "
                            "giving up on it\n")
                        responses[rid] = resp
                        del pending[rid]
                        continue
                    delay = resp.get("retry_after_s") or _DEFAULT_RETRY_S
                    if resp.get("retry_after_s"):
                        last_hint[0] = max(last_hint[0], float(delay))
                    retry_at[rid] = time.monotonic() + float(delay)
                    log(f"# serve_client: request {rid} deferred "
                        f"({err.split(':', 1)[0]}); retrying in "
                        f"{delay}s\n")
                else:
                    outstanding.discard(rid)
                    responses[rid] = resp  # hard rejection: final
                    del pending[rid]
        except OSError:
            attempts += 1
            ep_i += 1  # rotate: the next attempt tries an alternate
            if attempts > reconnects:
                raise
            log(f"# serve_client: connection to {endpoint} died with "
                f"{len(pending)} request(s) incomplete; reconnecting on "
                f"{endpoints[ep_i % len(endpoints)]} and re-submitting "
                f"({attempts}/{reconnects})\n")
            _reconnect_sleep()
        finally:
            try:
                conn.close()
            except OSError:
                pass
    return responses


def _socket_epilogue(endpoints, *, want_stats: bool,
                     shutdown: bool) -> list:
    """Optional stats fetch + shutdown on a short final connection (the
    first reachable endpoint)."""
    out = []
    if not (want_stats or shutdown):
        return out
    if isinstance(endpoints, str):
        endpoints = [endpoints]
    conn = None
    for ep in endpoints:
        try:
            conn = _connect(ep)
            break
        except OSError:
            continue
    if conn is None:
        return out
    try:
        wf = conn.makefile("w", encoding="utf-8")
        rf = conn.makefile("r", encoding="utf-8")
        if want_stats:
            wf.write(json.dumps({"op": "stats"}) + "\n")
        if shutdown:
            wf.write(json.dumps({"op": "shutdown"}) + "\n")
        wf.flush()
        conn.shutdown(socket.SHUT_WR)
        out = [json.loads(ln) for ln in rf if ln.strip()]
        conn.close()
    except OSError:
        pass
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("fasta")
    ap.add_argument("--islands-out", default="-",
                    help="output path ('-' = stdout)")
    ap.add_argument("--posterior", action="store_true",
                    help="soft decoding (MPM-path islands + mean confidence)")
    ap.add_argument("--tenant", default="default")
    ap.add_argument("--socket", help="connect to a running daemon's "
                    "AF_UNIX socket (single-endpoint alias of --connect)")
    ap.add_argument("--connect", action="append", default=[],
                    metavar="ENDPOINT",
                    help="daemon endpoint: an AF_UNIX path or tcp:HOST:PORT; "
                    "repeat for alternates — each connection failure "
                    "rotates to the next (router-tier failover)")
    ap.add_argument("--shutdown", action="store_true",
                    help="send {'op': 'shutdown'} after the last request "
                    "(socket mode; spawned daemons always shut down)")
    ap.add_argument("--platform", default=None,
                    help="spawn mode: forwarded to the daemon (-P)")
    ap.add_argument("--stats", action="store_true",
                    help="also request and print broker stats at the end")
    ap.add_argument("--id-base", type=int, default=0,
                    help="first request id (the mux daemon's id space is "
                    "daemon-wide: concurrent clients must use disjoint "
                    "ranges, e.g. --id-base 1000 / 2000)")
    ap.add_argument("--reconnects", type=int, default=3,
                    help="socket mode: reconnect budget — on socket death "
                    "the client reconnects and re-submits its incomplete "
                    "ids (see the module docstring for the journal "
                    "interaction)")
    args = ap.parse_args()

    kind = "posterior" if args.posterior else "decode"
    requests = [
        {
            "id": args.id_base + i, "kind": kind, "tenant": args.tenant,
            "name": name or f"rec{args.id_base + i}", "seq": seq,
        }
        for i, (name, seq) in enumerate(iter_fasta_text(args.fasta))
    ]

    endpoints = ([args.socket] if args.socket else []) + list(args.connect)
    if endpoints:
        responses = run_socket_session(
            endpoints, requests, reconnects=args.reconnects,
            log=sys.stderr.write,
        )
        resp_list = [responses[rid] for rid in sorted(responses)]
        resp_list += _socket_epilogue(
            endpoints, want_stats=args.stats, shutdown=args.shutdown
        )
    else:
        lines = [json.dumps(r) for r in requests]
        if args.stats:
            lines.append(json.dumps({"op": "stats"}))
        cmd = [sys.executable, "-m", "cpgisland_tpu", "serve"]
        if args.platform:
            cmd += ["--platform", args.platform]
        proc = subprocess.run(
            cmd, input="\n".join(lines) + "\n",
            capture_output=True, text=True, cwd=REPO,
        )
        if proc.returncode != 0:
            sys.stderr.write(proc.stderr)
            return proc.returncode
        resp_list = [
            json.loads(ln) for ln in proc.stdout.splitlines() if ln.strip()
        ]

    n_ok = 0
    out = sys.stdout if args.islands_out == "-" else open(args.islands_out, "w")
    try:
        for resp in resp_list:
            if "stats" in resp:
                sys.stderr.write(json.dumps(resp["stats"]) + "\n")
                continue
            if not resp.get("ok"):
                sys.stderr.write(f"request {resp.get('id')}: "
                                 f"{resp.get('error')}\n")
                continue
            n_ok += 1
            out.write(resp.get("islands_text", ""))
            if resp.get("kind") == "posterior":
                sys.stderr.write(
                    f"# {resp.get('id')}: mean_conf="
                    f"{resp.get('mean_conf', 0.0):.4f}\n"
                )
    finally:
        if out is not sys.stdout:
            out.close()
    sys.stderr.write(f"# {n_ok}/{len(requests)} requests ok\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
