#!/usr/bin/env python
"""Client for the `cpgisland serve` daemon: FASTA in, island calls out.

Reads FASTA records, submits each as a JSONL request (decode by default,
--posterior for soft decoding), and writes the returned island calls in
the reference's `beg end len gc oe` line format (with a record-name
column, like the batch CLI's multi-record output).

Transport: --socket PATH connects to a running daemon's AF_UNIX socket;
without it, the client SPAWNS `python -m cpgisland_tpu serve` as a
subprocess and talks over its stdin/stdout — the zero-setup smoke path.

Examples:

    # one-shot: spawn a daemon, decode a file through it
    python tools/serve_client.py genome.fa --islands-out i.txt --platform cpu

    # against a running daemon
    python -m cpgisland_tpu serve --socket /tmp/cpg.sock &
    python tools/serve_client.py genome.fa --socket /tmp/cpg.sock \
        --islands-out i.txt --shutdown
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def iter_fasta_text(path: str):
    """(name, sequence-text) per FASTA record — text only, no encoding:
    the DAEMON encodes on its transport thread (that is the overlap)."""
    name, parts = None, []
    seen_any = False
    with open(path) as f:
        for line in f:
            if line.startswith(">"):
                if seen_any:
                    yield name or "", "".join(parts)
                name = line[1:].strip().split()[0] if line[1:].strip() else ""
                parts = []
                seen_any = True
            else:
                s = line.strip()
                if s:
                    parts.append(s)
                    seen_any = True
    if seen_any:
        yield name or "", "".join(parts)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("fasta")
    ap.add_argument("--islands-out", default="-",
                    help="output path ('-' = stdout)")
    ap.add_argument("--posterior", action="store_true",
                    help="soft decoding (MPM-path islands + mean confidence)")
    ap.add_argument("--tenant", default="default")
    ap.add_argument("--socket", help="connect to a running daemon's socket")
    ap.add_argument("--shutdown", action="store_true",
                    help="send {'op': 'shutdown'} after the last request "
                    "(socket mode; spawned daemons always shut down)")
    ap.add_argument("--platform", default=None,
                    help="spawn mode: forwarded to the daemon (-P)")
    ap.add_argument("--stats", action="store_true",
                    help="also request and print broker stats at the end")
    ap.add_argument("--id-base", type=int, default=0,
                    help="first request id (the mux daemon's id space is "
                    "daemon-wide: concurrent clients must use disjoint "
                    "ranges, e.g. --id-base 1000 / 2000)")
    args = ap.parse_args()

    kind = "posterior" if args.posterior else "decode"
    requests = [
        json.dumps({
            "id": args.id_base + i, "kind": kind, "tenant": args.tenant,
            "name": name or f"rec{args.id_base + i}", "seq": seq,
        })
        for i, (name, seq) in enumerate(iter_fasta_text(args.fasta))
    ]
    if args.stats:
        requests.append(json.dumps({"op": "stats"}))

    if args.socket:
        import socket

        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.connect(args.socket)
        wf = conn.makefile("w", encoding="utf-8")
        rf = conn.makefile("r", encoding="utf-8")
        for line in requests:
            wf.write(line + "\n")
        if args.shutdown:
            wf.write(json.dumps({"op": "shutdown"}) + "\n")
        wf.flush()
        conn.shutdown(socket.SHUT_WR)
        out_lines = list(rf)
        conn.close()
    else:
        cmd = [sys.executable, "-m", "cpgisland_tpu", "serve"]
        if args.platform:
            cmd += ["--platform", args.platform]
        proc = subprocess.run(
            cmd, input="\n".join(requests) + "\n",
            capture_output=True, text=True, cwd=REPO,
        )
        if proc.returncode != 0:
            sys.stderr.write(proc.stderr)
            return proc.returncode
        out_lines = proc.stdout.splitlines()

    n_ok = 0
    out = sys.stdout if args.islands_out == "-" else open(args.islands_out, "w")
    try:
        for line in out_lines:
            line = line.strip()
            if not line:
                continue
            resp = json.loads(line)
            if "stats" in resp:
                sys.stderr.write(json.dumps(resp["stats"]) + "\n")
                continue
            if not resp.get("ok"):
                sys.stderr.write(f"request {resp.get('id')}: "
                                 f"{resp.get('error')}\n")
                continue
            n_ok += 1
            out.write(resp.get("islands_text", ""))
            if resp.get("kind") == "posterior":
                sys.stderr.write(
                    f"# {resp.get('id')}: mean_conf="
                    f"{resp.get('mean_conf', 0.0):.4f}\n"
                )
    finally:
        if out is not sys.stdout:
            out.close()
    sys.stderr.write(f"# {n_ok}/{len([r for r in requests if 'op' not in json.loads(r)])} requests ok\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
