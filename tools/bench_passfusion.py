"""Pass-fusion A/B harness: co-scheduled fwd/bwd vs the split 3-pass twins.

The r9 tentpole (BASELINE.md "Pass-count collapse") co-schedules the
probability-space forward and backward chains in ONE kernel launch
(fb_onehot._oh_fwdbwd_kernel), cutting the per-pass chain drains the r8
cost attribution blamed for the ~8-11 ms fixed per-iteration cost.  This
harness is the honest ship-or-negative A/B (the bench_compose.py
discipline): identical inputs, correctness-gated both arms, chained
timing, per-path plausibility ceilings — run it on the capturing TPU
before trusting any committed number.

Phases (each split/fused[/one_pass] on the SAME input):
  posterior   — seq_posterior_pallas conf path (3 -> 2 -> 1 passes; the
                one_pass arm is the ISSUE 17 matrix-carried kernel with
                the products pass folded in)
  em-seq      — seq_stats_pallas whole-sequence E-step (3 -> 2 -> 1)
  em-chunked  — batch_stats_pallas reference-framing E-step (2 -> 1 pass;
                no one_pass arm — the chunked layout never ran a
                standalone products pass)
  decode      — per-PASS wall decomposition of the 3-pass max-plus decode
                (products / +backpointers / +backtrace): the accounting
                that says what fraction each pass contributes; decode's
                passes are data-dependent (B needs A's entering vectors,
                C needs B's exits) so there is no fusion arm — the span
                driver instead overlaps the path DRAIN with the next
                span's compute (parallel.decode.viterbi_sharded_spans).

Relay rules (CLAUDE.md): chained reps inside one jit, a DISTINCT seed
folded into every rep (params-side for the FB paths so prepared streams
stay valid; one perturbed symbol for decode), every rep fetches a small
output, ceilings = the enforced BASELINE.md markers x2.5 via obs.watchdog.

Usage:
  python tools/bench_passfusion.py                     # TPU capture
  python tools/bench_passfusion.py --platform cpu --smoke   # CI slice
Prints ONE JSON line on stdout; progress goes to stderr.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _best_wall(fn, reps: int) -> float:
    """Min wall over reps with DISTINCT seeds; sub-100us walls are relay
    phantoms and retried (bench.py defense)."""
    seed, done, phantoms, best = 1, 0, 0, float("inf")
    while done < reps:
        t0 = time.perf_counter()
        fn(seed)
        dt = time.perf_counter() - t0
        seed += 1
        if dt < 1e-4:
            phantoms += 1
            if phantoms > 3 * reps:
                raise RuntimeError("persistent ~0 ms results: relay phantom")
            continue
        best = min(best, dt)
        done += 1
    return best


def _check_ceiling(tput: float, ceiling: float, what: str) -> None:
    if tput > ceiling:
        raise RuntimeError(
            f"{what}: {tput / 1e6:.0f} Msym/s exceeds the "
            f"{ceiling / 1e6:.0f} Msym/s plausibility ceiling (relay phantom?)"
        )


def _jitter(p, s):
    # Fold the FULL seed (no small modulus): _best_wall retries phantoms with
    # fresh seeds, and a wrapped jitter would hand the relay a byte-identical
    # repeat of the warm input (s=0) — the exact repeat the defense exists to
    # avoid.  Seeds stay O(reps), so the perturbation stays ~1e-6.
    import jax.numpy as jnp

    return dataclasses.replace(
        p, log_pi=p.log_pi - s.astype(jnp.float32) * 1e-7
    )


def bench_posterior(params, n, *, chain, reps, ceiling, lane_T, t_tile):
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.ops import fb_pallas

    rng = np.random.default_rng(1)
    obs = jnp.asarray(rng.integers(0, 4, size=n, dtype=np.int32).astype(np.uint8))
    mask = jnp.asarray(np.r_[np.ones(4), np.zeros(4)].astype(np.float32))

    ARMS = {"split": dict(fused=False), "fused": dict(fused=True),
            "one_pass": dict(one_pass=True)}

    def make(arm):
        kw = ARMS[arm]

        @jax.jit
        def chained(p, obs, s):
            p = _jitter(p, s)

            def body(c, _):
                conf, _ = fb_pallas.seq_posterior_pallas(
                    p, obs, n, mask + c * 0.0, lane_T=lane_T, t_tile=t_tile,
                    onehot=True, **kw,
                )
                return jnp.sum(conf[:8]) * 1e-9, None

            c, _ = jax.lax.scan(body, jnp.float32(0), None, length=chain)
            return c

        return chained

    out, raw = {}, {}
    # Correctness gate before timing: every arm on the same input.
    confs = {
        arm: fb_pallas.seq_posterior_pallas(
            params, obs, n, mask, lane_T=lane_T, t_tile=t_tile, onehot=True,
            **kw,
        )[0]
        for arm, kw in ARMS.items()
    }
    for arm in ("fused", "one_pass"):
        err = float(jnp.max(jnp.abs(confs["split"] - confs[arm])))
        assert err < 2e-5, f"posterior {arm} vs split diverged: {err}"
        log(f"posterior parity gate [{arm} vs split]: max|conf diff| = {err:.2e}")
    for arm in ARMS:
        fn = make(arm)
        jax.block_until_ready(fn(params, obs, jnp.int32(0)))
        best = _best_wall(
            lambda s, fn=fn: float(
                jax.device_get(fn(params, obs, jnp.int32(s)))
            ),
            reps,
        ) / chain
        tput = n / best
        _check_ceiling(tput, ceiling, "posterior")
        raw[arm] = tput
        out[arm] = round(tput / 1e6, 1)
        log(f"posterior [{arm}]: {tput / 1e6:8.1f} Msym/s ({best * 1e3:.2f} ms)")
    out["ratio"] = round(raw["fused"] / raw["split"], 3)
    # The decision number: flip one_pass.posterior only if this measures
    # > 1.03 on the capturing TPU (graftune margin rule).
    out["one_pass_ratio"] = round(raw["one_pass"] / raw["fused"], 3)
    return out


def bench_em_seq(params, n, *, chain, reps, ceiling, t_tile):
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.ops import fb_pallas
    from cpgisland_tpu.train.baum_welch import em_update

    rng = np.random.default_rng(2)
    obs = jnp.asarray(rng.integers(0, 4, size=n, dtype=np.int32).astype(np.uint8))
    lane_T = fb_pallas.pick_lane_T(n, onehot=True, long_lanes=True)

    ARMS = {"split": dict(fused=False), "fused": dict(fused=True),
            "one_pass": dict(one_pass=True)}

    def make(arm):
        kw = ARMS[arm]

        @jax.jit
        def chained(p, obs, s):
            p = _jitter(p, s)

            def body(p, _):
                st = fb_pallas.seq_stats_pallas(
                    p, obs, n, lane_T=lane_T, t_tile=t_tile, onehot=True,
                    **kw,
                )
                p2, _ = em_update(p, st)
                return p2, None

            p, _ = jax.lax.scan(body, p, None, length=chain)
            return p

        return chained

    stats = {
        arm: fb_pallas.seq_stats_pallas(
            params, obs, n, lane_T=lane_T, t_tile=t_tile, onehot=True, **kw
        )
        for arm, kw in ARMS.items()
    }
    for arm in ("fused", "one_pass"):
        err = float(
            jnp.max(jnp.abs(stats["split"].trans - stats[arm].trans)
                    / jnp.maximum(jnp.abs(stats["split"].trans), 1e-3))
        )
        assert err < 1e-4, f"em-seq {arm} vs split diverged: {err}"
        log(f"em-seq parity gate [{arm} vs split]: max rel trans diff = {err:.2e}")
    out, raw = {"lane_T": lane_T}, {}
    for arm in ARMS:
        fn = make(arm)
        jax.block_until_ready(fn(params, obs, jnp.int32(0)))
        best = _best_wall(
            lambda s, fn=fn: np.asarray(
                jax.device_get(fn(params, obs, jnp.int32(s)).log_pi)
            ).sum(),
            reps,
        ) / chain
        tput = n / best
        _check_ceiling(tput, ceiling, "em-seq")
        raw[arm] = tput
        out[arm] = round(tput / 1e6, 1)
        log(f"em-seq [{arm}]: {tput / 1e6:8.1f} Msym/s/iter ({best * 1e3:.2f} ms)")
    out["ratio"] = round(raw["fused"] / raw["split"], 3)
    # Flip one_pass.em_seq on TPU only past the 3% graftune margin.
    out["one_pass_ratio"] = round(raw["one_pass"] / raw["fused"], 3)
    return out


def bench_em_chunked(params, n, *, chain, reps, ceiling, chunk=1 << 16):
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.ops import fb_pallas
    from cpgisland_tpu.train.baum_welch import em_update

    rng = np.random.default_rng(3)
    n_chunks = max(1, n // chunk)
    chunks = jnp.asarray(
        rng.integers(0, 4, size=(n_chunks, chunk), dtype=np.int32).astype(np.uint8)
    )
    lengths = jnp.full(n_chunks, chunk, jnp.int32)
    total = n_chunks * chunk

    def make(fused):
        @jax.jit
        def chained(p, chunks, lengths, s):
            p = _jitter(p, s)

            def body(p, _):
                st = fb_pallas.batch_stats_pallas(
                    p, chunks, lengths, onehot=True, fused=fused
                )
                p2, _ = em_update(p, st)
                return p2, None

            p, _ = jax.lax.scan(body, p, None, length=chain)
            return p

        return chained

    s_s = fb_pallas.batch_stats_pallas(params, chunks, lengths, onehot=True, fused=False)
    s_f = fb_pallas.batch_stats_pallas(params, chunks, lengths, onehot=True, fused=True)
    err = float(
        jnp.max(jnp.abs(s_s.trans - s_f.trans)
                / jnp.maximum(jnp.abs(s_s.trans), 1e-3))
    )
    assert err < 1e-4, f"em-chunked fused vs split diverged: {err}"
    log(f"em-chunked parity gate: max rel trans diff = {err:.2e}")
    out, raw = {"n_chunks": n_chunks}, {}
    for fused in (False, True):
        fn = make(fused)
        jax.block_until_ready(fn(params, chunks, lengths, jnp.int32(0)))
        best = _best_wall(
            lambda s, fn=fn: np.asarray(
                jax.device_get(fn(params, chunks, lengths, jnp.int32(s)).log_pi)
            ).sum(),
            reps,
        ) / chain
        tput = total / best
        _check_ceiling(tput, ceiling, "em-chunked")
        arm = "fused" if fused else "split"
        raw[arm] = tput
        out[arm] = round(tput / 1e6, 1)
        log(f"em-chunked [{arm}]: {tput / 1e6:8.1f} Msym/s/iter ({best * 1e3:.2f} ms)")
    out["ratio"] = round(raw["fused"] / raw["split"], 3)
    return out


def bench_decode_passes(params, n, *, chain, reps, ceiling, bk=4096):
    """Per-pass wall decomposition of the 3-pass onehot decode: cumulative
    programs A / A+B / A+B+C on one stream; the differences attribute the
    wall to each pass.  Seeds perturb ONE symbol (decode has no
    params-side jitter that keeps paths comparable)."""
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.ops import viterbi_onehot as OH
    from cpgisland_tpu.ops.viterbi_parallel import (
        _block_passes,
        _enter_vectors,
        _step_tables,
    )

    rng = np.random.default_rng(4)
    S = params.n_symbols
    n_steps = n - 1
    bk = min(bk, max(8, n_steps))
    nb = -(-n_steps // bk)
    obs = rng.integers(0, 4, size=nb * bk + 1, dtype=np.int32)
    stream = jnp.asarray(obs)
    _, emit_ext = _step_tables(params)
    # Distinct-seed perturb with a LARGE period: seed picks both the position
    # and (past one position wrap) the value delta, so no rep — including
    # phantom retries — repeats the warm stream (s=0) or any earlier rep.
    P = min(8191, n_steps)

    def perturb(o, s):
        pos = 1 + (s * 7) % P
        return o.at[pos].set((o[pos] + 1 + s // P) % S)

    def setup(o):
        v0 = params.log_pi + emit_ext[o[0]]
        steps2 = o[1:].reshape(nb, bk).T
        return v0, steps2, o[0]

    @jax.jit
    def run_a(o, s):
        o = perturb(o, s)

        def body(c, _):
            v0, steps2, prev0 = setup(o)
            incl, offs, total = OH.pass_products(params, steps2, prev0=prev0)
            return c + jnp.sum(total) * 1e-9, None

        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=chain)
        return c

    @jax.jit
    def run_ab(o, s):
        o = perturb(o, s)

        def body(c, _):
            v0, steps2, prev0 = setup(o)
            incl, offs, _ = OH.pass_products(params, steps2, prev0=prev0)
            v_enter, _ = _enter_vectors(v0, incl, offs)
            delta_blocks, F, _blob = OH.pass_backpointers(
                params, v_enter, steps2, prev0
            )
            return c + jnp.sum(delta_blocks[-1]) * 1e-9, None

        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=chain)
        return c

    @jax.jit
    def run_abc(o, s):
        o = perturb(o, s)

        def body(c, _):
            v0, _, prev0 = setup(o)
            dec = _block_passes(
                params, v0, o[1:], bk, engine="onehot", prev0=prev0
            )
            return c + jnp.sum(dec.path[:8]).astype(jnp.float32) * 1e-9, None

        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=chain)
        return c

    walls = {}
    for name, fn in (("A", run_a), ("A+B", run_ab), ("A+B+C", run_abc)):
        jax.block_until_ready(fn(stream, jnp.int32(0)))
        walls[name] = _best_wall(
            lambda s, fn=fn: float(jax.device_get(fn(stream, jnp.int32(s)))),
            reps,
        ) / chain
        log(f"decode passes [{name}]: {walls[name] * 1e3:.2f} ms")
    tput = n / walls["A+B+C"]
    _check_ceiling(tput, ceiling, "decode")
    per_pass = {
        "products_ms": round(walls["A"] * 1e3, 3),
        "backpointers_ms": round((walls["A+B"] - walls["A"]) * 1e3, 3),
        "backtrace_ms": round((walls["A+B+C"] - walls["A+B"]) * 1e3, 3),
        "total_ms": round(walls["A+B+C"] * 1e3, 3),
        "msym_per_s": round(tput / 1e6, 1),
    }
    if min(per_pass["backpointers_ms"], per_pass["backtrace_ms"]) < 0:
        # Differences of independently-noised walls: a negative delta means
        # the reps/size are too small to attribute — do not publish it.
        per_pass["noisy"] = True
        log("decode per-pass: NEGATIVE delta — noise; raise --reps/--mib "
            "before publishing this table")
    log(
        "decode per-pass: products {products_ms} ms, backpointers "
        "{backpointers_ms} ms, backtrace {backtrace_ms} ms".format(**per_pass)
    )
    return per_pass


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="auto")
    ap.add_argument("--mib", type=int, default=64)
    ap.add_argument("--chain", type=int, default=6)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--t-tile", type=int, default=512)
    ap.add_argument(
        "--smoke", action="store_true",
        help="tiny CPU sizes: parity gates + one timing rep per arm (CI)",
    )
    ap.add_argument(
        "--sweep-lanes", action="store_true",
        help="additionally re-sweep lane_T over _LANE_RATE_ONEHOT's keys "
        "for the FUSED posterior/em-seq arms (the standing 'swept once "
        "rots' obligation after a kernel reshape — run on the capturing "
        "TPU and update the rate table from the result)",
    )
    args = ap.parse_args()

    import jax

    if args.platform != "auto":
        jax.config.update("jax_platforms", args.platform)

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.obs import watchdog

    params = presets.durbin_cpg8()
    on_tpu = jax.default_backend() == "tpu"
    if args.smoke:
        n = 256 << 10
        chain, reps = 2, 1
        lane_T = 2048
    elif not on_tpu:
        # CPU projection: structure + parity only — a serial machine cannot
        # observe chain-latency overlap, so ratios here are NOT the chip
        # answer (see BASELINE.md "Pass-count collapse").
        n = min(args.mib, 4) << 20
        chain, reps = 2, 2
        lane_T = 8192
    else:
        n = args.mib << 20
        chain, reps = args.chain, args.reps
        lane_T = None
    ceilings = watchdog.path_ceilings() if on_tpu else {}
    inf = float("inf")

    from cpgisland_tpu.ops import fb_pallas

    results = {
        "bench": "passfusion",
        "backend": jax.default_backend(),
        "n_mi": n >> 20,
        "chain": chain,
        "projection": not on_tpu,
    }
    results["posterior"] = bench_posterior(
        params, n, chain=chain, reps=reps,
        ceiling=ceilings.get("posterior", inf),
        lane_T=lane_T or fb_pallas.pick_lane_T(n, onehot=True, long_lanes=True),
        t_tile=args.t_tile,
    )
    results["em_seq"] = bench_em_seq(
        params, n, chain=chain, reps=reps,
        ceiling=ceilings.get("em-seq", inf), t_tile=args.t_tile,
    )
    results["em_chunked"] = bench_em_chunked(
        params, n, chain=chain, reps=reps,
        ceiling=ceilings.get("em", inf),
        chunk=(1 << 16) if n >= (1 << 20) else (n // 4),
    )
    results["decode_passes"] = bench_decode_passes(
        params, n, chain=chain, reps=reps,
        ceiling=ceilings.get("decode", inf),
        bk=4096 if on_tpu else 512,
    )
    if args.sweep_lanes:
        # Re-sweep the fused kernel's lane length (its VMEM working set and
        # issue mix differ from the split kernels the current
        # _LANE_RATE_ONEHOT table was swept for).
        sweep = {}
        for lt in sorted(fb_pallas._LANE_RATE_ONEHOT):
            if lt > n:
                continue
            try:
                row = bench_posterior(
                    params, n, chain=chain, reps=reps,
                    ceiling=ceilings.get("posterior", inf),
                    lane_T=lt, t_tile=args.t_tile,
                )
            except Exception as e:  # a lane length that fails to compile
                sweep[str(lt)] = f"failed: {type(e).__name__}"
                log(f"lane sweep {lt}: {e}")
                continue
            sweep[str(lt)] = row
            log(f"lane sweep {lt}: fused {row['fused']} Msym/s")
        results["lane_sweep_posterior"] = sweep
    print(json.dumps(results))
    return 0


if __name__ == "__main__":
    sys.exit(main())
