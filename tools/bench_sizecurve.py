"""em-seq SIZE CURVE harness: per-iteration rate at 16/32/64 Mi in ONE run.

BASELINE.md's r5 finding: the exact whole-sequence E-step follows a size
curve (~763 Msym/s/iter @16 Mi vs ~1050 @64 Mi on chip), implying ~8-11 ms
of FIXED per-iteration in-graph cost (boundary glue + stats assembly +
M-step + symbol-stream re-prep) that small inputs cannot amortize.  This
harness measures that curve directly, A/B-ing inline prep vs a
PreparedStreams-threaded loop (ops.prepared) so the fixed-cost reduction is
a committed artifact, not a code comment.

Relay-safe by construction (the CLAUDE.md bench rules):
- ``chain`` EM iterations run inside one jit (params feed forward through
  the fused M-step+delta epilogue), so one blocking fetch covers the chain;
- every timing rep folds a DISTINCT seed into its input — into the PARAMS
  (a per-rep log_pi jitter), not the symbols, so the prepared streams stay
  valid across reps — and fetches a small output;
- per-path plausibility ceilings come from obs.watchdog (the enforced
  BASELINE.md em-seq marker x2.5); any rep over the ceiling aborts the
  phase rather than entering the artifact.

Usage:
  python tools/bench_sizecurve.py                  # TPU: 16,32,64 Mi
  python tools/bench_sizecurve.py --platform cpu --sizes-mi 1,2,4 --chain 2
                                                   # CPU projection (CI)

Prints ONE JSON line on stdout; progress goes to stderr.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _best_wall(fn, reps: int = 5) -> float:
    """Min wall of fn(seed) over reps with DISTINCT seeds (fn blocks
    internally); sub-100us walls are treated as relay phantoms and retried
    with fresh seeds (same defense as bench.py)."""
    seed, done, phantoms, best = 1, 0, 0, float("inf")
    while done < reps:
        t0 = time.perf_counter()
        fn(seed)
        dt = time.perf_counter() - t0
        seed += 1
        if dt < 1e-4:
            phantoms += 1
            if phantoms > 3 * reps:
                raise RuntimeError("persistent ~0 ms results: relay phantom")
            continue
        best = min(best, dt)
        done += 1
    return best


def bench_size(params, n: int, *, chain: int, onehot: bool, t_tile: int,
               use_prepared: bool, ceiling: float) -> dict:
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.ops import fb_pallas, prepared as prep_mod
    from cpgisland_tpu.train.baum_welch import em_update

    rng = np.random.default_rng(6)
    stream = jnp.asarray(
        rng.integers(0, 4, size=n, dtype=np.int32).astype(np.uint8)
    )
    long_ok = onehot and params.n_symbols & (params.n_symbols - 1) == 0
    lane_T = fb_pallas.pick_lane_T(n, onehot=onehot, long_lanes=long_ok)
    prep = (
        prep_mod.for_seq(
            params.n_symbols, stream, n, lane_T=lane_T, t_tile=t_tile,
            onehot=onehot,
        )
        if use_prepared
        else None
    )

    @jax.jit
    def chained(p, obs, prep, s):
        # Distinct-seed fold into the PARAMS (symbols must stay fixed so
        # the prepared streams remain valid): a tiny per-rep log_pi jitter
        # makes every rep a distinct request without moving the numbers.
        p = dataclasses.replace(
            p, log_pi=p.log_pi - (s % 7).astype(jnp.float32) * 1e-7
        )

        def body(p, _):
            st = fb_pallas.seq_stats_pallas(
                p, obs, n, lane_T=lane_T, t_tile=t_tile, onehot=onehot,
                prepared=prep,
            )
            p2, _delta = em_update(p, st)
            return p2, None

        p, _ = jax.lax.scan(body, p, None, length=chain)
        return p

    jax.block_until_ready(chained(params, stream, prep, jnp.int32(0)))
    best = _best_wall(
        lambda s: np.asarray(
            jax.device_get(chained(params, stream, prep, jnp.int32(s)).log_pi)
        ).sum()
    ) / chain
    tput = n / best
    if tput > ceiling:
        raise RuntimeError(
            f"em-seq sizecurve: {tput/1e6:.0f} Msym/s/iter exceeds the "
            f"{ceiling/1e6:.0f} Msym/s plausibility ceiling (relay phantom?)"
        )
    return {
        "n_mi": n >> 20, "lane_T": lane_T, "prepared": use_prepared,
        "wall_ms_per_iter": round(best * 1e3, 3),
        "msym_per_s_per_iter": round(tput / 1e6, 1),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="auto")
    ap.add_argument("--sizes-mi", default="16,32,64")
    ap.add_argument("--chain", type=int, default=8)
    ap.add_argument("--t-tile", type=int, default=512)
    ap.add_argument("--engine", default="auto")
    args = ap.parse_args()

    import jax

    if args.platform != "auto":
        jax.config.update("jax_platforms", args.platform)

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.obs import watchdog
    from cpgisland_tpu.ops import fb_onehot

    params = presets.durbin_cpg8()
    on_tpu = jax.default_backend() == "tpu"
    onehot = (
        args.engine == "onehot"
        or (args.engine == "auto" and fb_onehot.supports(params))
    )
    # Plausibility: the enforced em-seq marker x2.5 (obs.watchdog parses it
    # from BASELINE.md); off-TPU there is no meaningful marker — keep the
    # absolute insanity bound only.
    ceilings = watchdog.path_ceilings()
    ceiling = ceilings.get("em-seq", float("inf")) if on_tpu else float("inf")

    sizes = [int(s) << 20 for s in args.sizes_mi.split(",")]
    rows = []
    for n in sizes:
        for use_prepared in (False, True):
            row = bench_size(
                params, n, chain=args.chain, onehot=onehot,
                t_tile=args.t_tile, use_prepared=use_prepared,
                ceiling=ceiling,
            )
            rows.append(row)
            log(
                f"em-seq {row['n_mi']:>4} Mi "
                f"[{'prepared' if use_prepared else 'inline  '}]: "
                f"{row['msym_per_s_per_iter']:8.1f} Msym/s/iter "
                f"({row['wall_ms_per_iter']:.2f} ms/iter, lane_T={row['lane_T']})"
            )
    # Fixed-cost estimate per size: the inline-minus-prepared wall is the
    # per-iteration symbol-prep share; the residual fixed cost shows as the
    # rate still rising with size.
    fixed = {}
    for n in sizes:
        mi = n >> 20
        w_in = next(r for r in rows if r["n_mi"] == mi and not r["prepared"])
        w_pr = next(r for r in rows if r["n_mi"] == mi and r["prepared"])
        fixed[str(mi)] = round(
            w_in["wall_ms_per_iter"] - w_pr["wall_ms_per_iter"], 3
        )
        log(f"  prep share @ {mi} Mi: {fixed[str(mi)]} ms/iter")
    print(json.dumps({
        "bench": "em-seq-sizecurve",
        "backend": jax.default_backend(),
        "engine": "onehot" if onehot else "dense",
        "chain": args.chain,
        "rows": rows,
        "prep_ms_per_iter": fixed,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
