#!/usr/bin/env bash
# CI gate: graftcheck (lint + jaxpr contracts) + ruff/mypy when available +
# a tier-1 smoke slice.  Exits non-zero on any violation.  Runs entirely on
# CPU — no TPU needed (the contract pass pins jax_platforms=cpu itself).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== graftcheck: AST lint (TPU invariants) =="
python -m cpgisland_tpu.analysis cpgisland_tpu/

echo "== graftcheck: jaxpr contract pass (CPU trace) =="
# Includes em.body.invariant-free: the fused EM while-body must contain no
# symbol-stream prep primitives (prepared streams resolved outside the loop).
python -m cpgisland_tpu.analysis --no-lint --contracts

echo "== graftcost: quantitative cost contracts + COSTS.json diff (CPU trace) =="
# Layer 3: live cost fingerprints (FLOPs/bytes/serial depth/pass counts at
# >=2 geometries) must match the committed lockfile; a drift names the
# drifting primitives.  Re-baseline with --update-costs after a VERIFIED
# graph change.
python -m cpgisland_tpu.analysis --no-lint --costs

echo "== graftmem: Layer-5 memory contracts + MEMORY.json diff (CPU trace) =="
# Layer 5: HBM liveness fingerprints (peak live bytes at >=2 geometries,
# named O(T) allocation groups) + the shipped-knob VMEM footprint of every
# modeled kernel must match the committed lockfile; the memory contracts
# pin the VMEM budget (incl. stacked M=3), the blocked island reduction's
# O(block)-not-O(T) temps, the derived 112 Mi seq-shard cap, and the
# stacked-M envelope.  Re-baseline with --update-mem after a VERIFIED
# change.
python -m cpgisland_tpu.analysis --no-lint --mem

echo "== graftscale: Layer-6 scale contracts + SCALE.json freshness (CPU trace) =="
# Layer 6: the jaxpr homogeneity dataflow derives each registered
# fused/one-pass direction consumer's scale signature and checks it
# against BOTH the ops modules' SCALE_TAGS declarations and the
# committed SCALE.json (fingerprint-keyed on COSTS.json: a kernel
# reshape STALES the signature to a report-only note — re-derive with
# --update-scale).  The runtime half is fb_onehot.run_stats_onehot's
# betas_scale route guard; the r9 "that pairing is a bug" class fails
# HERE, statically, before any chip time is spent.
python -m cpgisland_tpu.analysis --no-lint --scale

echo "== graftsync: Layer-4 cross-module lock-order graph =="
# The per-file concurrency rules (sync-guarded-by / sync-lock-order /
# sync-blocking-under-lock / sync-thread-lifecycle) already ran inside the
# lint gate above; --sync adds the cross-module acquires-while-holding
# graph — a cycle is a static deadlock that would freeze the serve daemon
# AND strand in-flight TPU dispatches behind held locks.
python -m cpgisland_tpu.analysis --no-lint --sync

echo "== syntax gate =="
python -m compileall -q cpgisland_tpu tools tests bench.py __graft_entry__.py

# The container this repo grows in has neither ruff nor mypy baked in (and
# installing deps is off-limits there); graftcheck's hygiene rules carry
# the unused-import/shadowing checks meanwhile.  Both run here when the
# host provides them, against the pyproject.toml baselines.
if command -v ruff >/dev/null 2>&1; then
  echo "== ruff =="
  ruff check .
else
  echo "== ruff not on PATH: skipped (baseline config in [tool.ruff]) =="
fi

if command -v mypy >/dev/null 2>&1; then
  echo "== mypy (basic) =="
  mypy cpgisland_tpu
else
  echo "== mypy not on PATH: skipped (baseline config in [tool.mypy]) =="
fi

echo "== tier-1 smoke =="
python -m pytest tests/test_graftcheck.py tests/test_graftcheck_self.py \
  tests/test_graftscale.py tests/test_hmm.py tests/test_viterbi.py -q

echo "== fault-injection & resilience slice =="
# The recovery machinery is only trustworthy while its injected-fault tests
# stay green: real in-jit XlaRuntimeErrors through fit() AND the serving
# paths (decode/posterior supervision, breaker ladder, manifest resume,
# elastic micro-batch retry).
python -m pytest tests/test_fault_injection.py tests/test_elastic.py \
  tests/test_resilience.py -q

echo "== prepared-streams smoke (parity + cache + zero-reprep ledger) =="
python -m pytest tests/test_prepared.py -q

echo "== pass-fusion smoke (co-scheduled fwd/bwd parity + A/B harness) =="
# The r9 fused pass vs its split 3-pass twins (tests), then the A/B
# harness's parity gates + one CPU timing rep per arm (--smoke; the
# committed chip figures come from running it WITHOUT --smoke on the
# capturing TPU).
python -m pytest tests/test_passfusion.py -q
python tools/bench_passfusion.py --platform cpu --smoke > /dev/null

echo "== graftune smoke (prune -> parity-gate -> time -> persist cycle) =="
# The knob autotuner's CI slice: one task per kernel family/engine
# (reduced FB lane + t_tile, flat decode block, stacked EM, a fused
# verdict) runs the full cycle on CPU against a THROWAWAY table (the
# committed TUNING.json stays untouched), with the ledger asserting zero
# memmodel-rejected tuples ever reached compile.  Then the table tests:
# fresh-winner consultation, bit-for-bit legacy fallback on
# absent/stale/fingerprint-drifted entries, the absurd-winner parity
# gate, and the committed-table freshness pin.
python -m pytest tests/test_graftune.py -q
_tune_tmp="$(mktemp -d)"
python tools/graftune.py --platform cpu --smoke --update-tune --apply \
  --tune-file "$_tune_tmp/TUNING.json" > /dev/null
rm -rf "$_tune_tmp"
python -m cpgisland_tpu.analysis --no-lint --tune

echo "== serve smoke (broker vs batch pipelines, transport, restart) =="
# The serving daemon's acceptance surface: an in-process broker streaming
# mixed decode+posterior requests across two tenants, results BIT-IDENTICAL
# to decode_file/posterior_file on the same records with zero fresh
# compiles / zero prepared-cache re-preps after the first flush of each
# geometry — plus flush policy, admission caps, per-session breaker,
# manifest restart, and the JSONL transport.  (The contract pass above
# already pins serve.flush.dispatch-stable.)
python -m pytest tests/test_serve.py -q

echo "== multi-model stacking smoke (stacked-vs-sequential bit-identity + A/B harness) =="
# r12: N members' reduced chains in ONE stacked launch set.  The tests pin
# per-member BIT-identity against the sequential arm (decode paths+scores,
# conf tracks, compare loglik/winner, EM stats; 2/3/5-member sets incl.
# the dinuc pair-lift), the serve stacked flush routes, the shared
# per-order placement ledger, and the planted DE-stacked fixture failing
# the graftcost pass pin.  The harness then runs its bit-identity gates +
# one CPU timing rep per arm (--smoke; chip ratios come from running it
# WITHOUT --smoke on the capturing TPU).
python -m pytest tests/test_multimodel.py -q
python tools/bench_multimodel.py --platform cpu --smoke > /dev/null

echo "== model-family & compare smoke (partition oracle, member parity, compare workload) =="
# The family layer's acceptance surface: family.partition_of as the single
# eligibility oracle (all four routers agree on every preset), dense-vs-
# reduced parity for the new members (dinuc/pair alphabet, random
# partition families), the 3-model compare workload bit-identical to
# independent posterior runs with zero fresh compiles on the second
# stream, and the serve registry (model= routing, compare requests,
# per-model breaker isolation).
python -m pytest tests/test_family.py tests/test_serve_family.py -q

echo "== graftsync slice: rule fixtures, tracker, threaded serve-mux stress =="
# Layer 4's own tests (planted deadlock/unguarded-access fixtures must each
# FAIL naming the offending locks/attributes; repo self-scan + lock graph
# stay pinned — the r15 fleet/journal/faultplan locks included), then the
# multi-connection socket mux under the runtime tracker: 4 concurrent
# clients, mixed decode+posterior, bit-identical per client, zero observed
# lock-order or guarded-access violations — including the 2-device
# DevicePool run with one device quarantined mid-stream.
python -m pytest tests/test_graftsync.py tests/test_graftsync_self.py \
  tests/test_serve_mux.py -q

echo "== graftscope slice (lineage, SLO histograms, flight recorder, stats wire) =="
# PR 16: request-scoped serve telemetry.  Trace lineage closes every
# admitted request across broker/journal/queue/flush stations (stdio AND
# socket mux), the log-binned histograms merge exactly under 8 concurrent
# writers, the flight recorder's ring stays bounded and its postmortem
# artifact survives a SimulatedKill (persisted BEFORE the kill
# propagates), kind=stats answers inline with the SLO snapshot, and the
# ledger proves the telemetry-off serve path issues IDENTICAL device
# work to telemetry-on (the zero-overhead-off acceptance gate).
python -m pytest tests/test_graftscope.py -q

echo "== graftfault chaos slice (seeded plan matrix on the virtual mesh) =="
# r15: every fleet failover path driven by deterministic fault plans —
# device fault past the retry budget mid-flush (quarantine -> requeue ->
# half-open probe -> restore), phantom-result quarantine, never-kill
# slow-dispatch quarantine, connection death mid-stream recovered by the
# client's reconnect-with-replay, and SIGKILL planted at each journal
# phase boundary (write-ahead admit -> completion) with restart replay.
# Every plan must converge BIT-IDENTICAL to the fault-free run with zero
# dropped admitted requests and a fully-ledgered requeue/replay trail.
python -m pytest tests/test_graftfault.py -q

echo "== serve router + host-chaos slice (pod-scale tier under the tracker) =="
# PR 20: the multi-host routing tier.  Per-host health machines (terminal
# DEAD included), least-loaded routing bit-identical to the single-broker
# batch run, the measured-flush-wall retry_after_s load-shedding contract,
# all-hosts-saturated shedding + drain-via-quarantine + half-open restore,
# and the host-death chaos matrix: a host SIGKILLed mid-flush (plus the
# seeded faultplan.host_matrix) must fail its journaled admissions over to
# the survivor BIT-IDENTICALLY — zero drops, zero double executions
# (journal-audited), both host memberships in the graftscope lineage.
# Runs under the graftsync runtime tracker (CPGISLAND_TRACKSYNC=1): the
# router/health locks join the watched set across the whole file.
CPGISLAND_TRACKSYNC=1 python -m pytest tests/test_serve_router.py -q

echo "ci_checks: all gates green"
