"""Render a cpgisland_tpu obs metrics JSONL into a per-phase summary table.

    python tools/obs_report.py metrics.jsonl

Output: one fixed-width table — per-phase wall, item counts, throughput,
blocking dispatches, cache-miss compiles, transfer bytes — followed by the
engine chosen per phase, the deduped decision counts, ledger totals, and any
plausibility-watchdog flags.  The rendering lives in
``cpgisland_tpu.obs.report`` (shared with the CLI's ``--obs-report``); this
is the thin file-level entry point.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cpgisland_tpu.obs import report  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("metrics_jsonl", help="JSONL written by --metrics / --metrics-out")
    args = ap.parse_args(argv)
    print(report.render_file(args.metrics_jsonl))
    return 0


if __name__ == "__main__":
    sys.exit(main())
