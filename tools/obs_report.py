"""Render a cpgisland_tpu obs metrics JSONL into a per-phase summary table.

    python tools/obs_report.py metrics.jsonl

Output: one fixed-width table — per-phase wall, item counts, throughput,
blocking dispatches, cache-miss compiles, transfer bytes — followed by the
engine chosen per phase, the deduped decision counts, ledger totals, and any
plausibility-watchdog flags.  The rendering lives in
``cpgisland_tpu.obs.report`` (shared with the CLI's ``--obs-report``); this
is the thin file-level entry point.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cpgisland_tpu.obs import report  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "metrics_jsonl", nargs="?", default=None,
        help="JSONL written by --metrics / --metrics-out (optional when "
        "only rendering a --flight dump — after a crash the flight "
        "artifact may be all that survived)",
    )
    ap.add_argument(
        "--request", type=int, default=None, metavar="ID",
        help="render only request ID's graftscope lineage (hop table)",
    )
    ap.add_argument(
        "--flight", metavar="PATH",
        help="also render a flight-recorder dump (*.flight.json) as an "
        "event timeline",
    )
    args = ap.parse_args(argv)
    if args.metrics_jsonl is None and not args.flight:
        ap.error("need a metrics JSONL and/or --flight PATH")
    if args.metrics_jsonl is None:
        pass
    elif args.request is not None:
        summary = report.summarize_jsonl(args.metrics_jsonl)
        print(report.render_lineage(
            summary.get("request_traces") or [], args.request
        ))
    else:
        print(report.render_file(args.metrics_jsonl))
    if args.flight:
        print()
        print(report.render_flight(args.flight))
    return 0


if __name__ == "__main__":
    sys.exit(main())
