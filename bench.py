"""Benchmark harness — prints ONE JSON line for the driver.

Metric: projected wall-clock for the north-star workload (BASELINE.json) on a
v5e-8 — Viterbi-decode all of GRCh38 (3.1 Gbp) AND run 10 Baum-Welch EM
iterations over a chr1-scale (250 Mbp) training set — assuming linear scaling
from the single measured chip to 8 chips (the sharded paths communicate only
[K,K]/[K] tensors per step, so scaling is effectively embarrassing).

vs_baseline = 60 s / projected_s: the north star is "< 60 s on one v5e-8", so
vs_baseline > 1.0 means the target is beaten, and by how much.  (The reference
itself publishes no numbers — BASELINE.md — so the north star is the bar.)

Usage: python bench.py [--decode-mib 256] [--em-chunks 512] [--engine auto]
       [--platform auto] [--extended]
(On CPU the decode size is capped at 16 MiB unless --decode-mib is given
explicitly — the 256 MiB default exists for TPU steady-state numbers.)
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

GRCH38_SYMBOLS = 3.1e9  # ~GRCh38 assembly length in bases
EM_TRAIN_SYMBOLS = 250e6  # chr1-scale training set (BASELINE.md config 2)
EM_ITERS = 10
TARGET_SECONDS = 60.0
N_CHIPS = 8  # v5e-8


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def bench_decode(n_symbols: int, engine: str = "auto", params=None, tag: str = "") -> float:
    """Measure single-chip blockwise-parallel Viterbi throughput (sym/s)."""
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel
    from cpgisland_tpu.parallel.decode import resolve_engine

    if params is None:
        params = presets.durbin_cpg8()
    eng = resolve_engine(engine, params)
    rng = np.random.default_rng(0)
    obs = jnp.asarray(rng.integers(0, 4, size=n_symbols, dtype=np.int32))
    fn = jax.jit(lambda o: viterbi_parallel(params, o, return_score=False, engine=eng))
    path = fn(obs)
    path.block_until_ready()  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        fn(obs).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    tput = n_symbols / best
    log(f"decode{tag}[{eng}]: {tput/1e6:.1f} Msym/s ({best*1e3:.0f} ms / {n_symbols/2**20:.0f} MiB)")
    return tput


def bench_em(n_chunks: int, chunk_size: int = 0x10000, engine: str = "auto") -> float:
    """Measure single-chip E-step+M-step throughput (sym/s per EM iteration).

    Default n_chunks=512 ~= the per-chip share of the chr1-scale EM workload on
    a v5e-8 (250e6 / 65536 / 8 chips ~= 477 chunks), so the measured batch is
    representative of what each chip actually processes.
    """
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.train.backends import LocalBackend, resolve_fb_engine
    from cpgisland_tpu.train.baum_welch import mstep

    params = presets.durbin_cpg8()
    eng = resolve_fb_engine(engine, params, "rescaled")
    backend = LocalBackend(mode="rescaled", engine=eng)
    rng = np.random.default_rng(1)
    chunks = jnp.asarray(rng.integers(0, 4, size=(n_chunks, chunk_size), dtype=np.int32).astype(np.uint8))
    lengths = jnp.full(n_chunks, chunk_size, dtype=jnp.int32)

    @jax.jit
    def em_iter(p):
        return mstep(p, backend(p, chunks, lengths))

    p = em_iter(params)
    jax.block_until_ready(p)  # compile + warm
    best = float("inf")
    for _ in range(5):  # EM timings are noisier than decode; take best of 5
        t0 = time.perf_counter()
        jax.block_until_ready(em_iter(params))
        best = min(best, time.perf_counter() - t0)
    n_sym = n_chunks * chunk_size
    tput = n_sym / best
    log(f"em[{eng}]: {tput/1e6:.1f} Msym/s/iter ({best*1e3:.0f} ms / {n_sym/2**20:.0f} MiB)")
    return tput


def bench_batched_decode(n_seqs: int, seq_len: int, engine: str = "auto") -> float:
    """Batched (vmap) multi-genome decode throughput in sym/s (BASELINE.md
    config 5): N independent sequences decoded as one [N, T] batch."""
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel_batch
    from cpgisland_tpu.parallel.decode import resolve_engine

    params = presets.durbin_cpg8()
    eng = resolve_engine(engine, params)
    rng = np.random.default_rng(2)
    chunks = jnp.asarray(rng.integers(0, 4, size=(n_seqs, seq_len), dtype=np.int32))
    lengths = jnp.full(n_seqs, seq_len, dtype=jnp.int32)
    fn = jax.jit(
        lambda c, l: viterbi_parallel_batch(params, c, l, return_score=False, engine=eng)
    )
    fn(chunks, lengths).block_until_ready()
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        fn(chunks, lengths).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    n_sym = n_seqs * seq_len
    tput = n_sym / best
    log(
        f"batched-decode[{eng}]: {tput/1e6:.1f} Msym/s "
        f"({n_seqs} x {seq_len/2**20:.0f} MiB in {best*1e3:.0f} ms)"
    )
    return tput


def bench_em_2state(n_chunks: int, chunk_size: int = 0x10000) -> float:
    """2-state model EM throughput in sym/s/iter (BASELINE.md config 2)."""
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.train.backends import LocalBackend
    from cpgisland_tpu.train.baum_welch import mstep

    params = presets.two_state_cpg()
    # auto resolves to the Pallas E-step kernels on TPU (they handle any
    # n_states <= 8, not just the flagship 8-state shape): ~7x the XLA scan.
    from cpgisland_tpu.train.backends import resolve_fb_engine

    eng = resolve_fb_engine("auto", params, "rescaled")
    backend = LocalBackend(mode="rescaled", engine=eng)
    rng = np.random.default_rng(3)
    chunks = jnp.asarray(rng.integers(0, 4, size=(n_chunks, chunk_size), dtype=np.int32).astype(np.uint8))
    lengths = jnp.full(n_chunks, chunk_size, dtype=jnp.int32)

    @jax.jit
    def em_iter(p):
        return mstep(p, backend(p, chunks, lengths))

    jax.block_until_ready(em_iter(params))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(em_iter(params))
        best = min(best, time.perf_counter() - t0)
    tput = n_chunks * chunk_size / best
    log(f"em-2state[{eng}]: {tput/1e6:.1f} Msym/s/iter ({best*1e3:.0f} ms)")
    return tput


def main() -> int:
    ap = argparse.ArgumentParser()
    # 256 MiB = the clean path's per-span decode unit (pipeline.CLEAN_DECODE_SPAN)
    # and ~one large chromosome — the size the north-star workload actually
    # decodes at; 64 MiB understates steady-state throughput by ~30%.  None =
    # resolve after the backend is known (256 on TPU, 16 on CPU where 256 MiB
    # would take minutes at ~4 Msym/s for no benefit).
    ap.add_argument("--decode-mib", type=int, default=None)
    ap.add_argument("--em-chunks", type=int, default=512)
    ap.add_argument("--engine", default="auto", choices=("auto", "xla", "pallas"))
    ap.add_argument("--platform", default="auto", help="auto|cpu|tpu (axon ignores JAX_PLATFORMS)")
    ap.add_argument(
        "--extended",
        action="store_true",
        help="also measure BASELINE.md configs (batched multi-genome decode, "
        "2-state EM); extra results go to stderr, stdout stays one JSON line",
    )
    args = ap.parse_args()

    import jax

    if args.platform != "auto":
        jax.config.update("jax_platforms", args.platform)
    log(f"devices: {jax.devices()}")
    if args.decode_mib is None:
        args.decode_mib = 256 if jax.default_backend() == "tpu" else 16

    decode_tput = bench_decode(args.decode_mib * (1 << 20), engine=args.engine)
    em_tput = bench_em(args.em_chunks, engine=args.engine)

    if args.extended:
        from cpgisland_tpu.models import presets as _presets

        CHR21, CHR1 = 46.7e6, 248e6
        batched_tput = bench_batched_decode(16, 4 << 20, engine=args.engine)
        em2_tput = bench_em_2state(256)
        decode2_tput = bench_decode(
            args.decode_mib * (1 << 20), engine=args.engine,
            params=_presets.two_state_cpg(), tag="-2state",
        )
        extras = {
            "chr21_2state_decode_projected_s": round(CHR21 / decode2_tput, 3),
            "chr1_8state_decode_plus_islands_projected_v5e8_s": round(
                CHR1 / (decode_tput * N_CHIPS), 3
            ),
            "em_2state_chr1_iters_per_sec_v5e8": round(
                em2_tput * N_CHIPS / EM_TRAIN_SYMBOLS, 2
            ),
            "em_8state_chr1_iters_per_sec_v5e8": round(
                em_tput * N_CHIPS / EM_TRAIN_SYMBOLS, 2
            ),
            "grch38_decode_projected_v5e8_s": round(
                GRCH38_SYMBOLS / (decode_tput * N_CHIPS), 3
            ),
            "batched_decode_genomes_per_sec_v5e8": round(
                batched_tput * N_CHIPS / GRCH38_SYMBOLS, 3
            ),
            "batched_decode_msym_per_sec_chip": round(batched_tput / 1e6, 1),
        }
        log("extended: " + json.dumps(extras))

    projected = GRCH38_SYMBOLS / (decode_tput * N_CHIPS) + EM_ITERS * EM_TRAIN_SYMBOLS / (
        em_tput * N_CHIPS
    )
    log(
        f"projected v5e-8 north-star workload: {projected:.2f} s "
        f"(decode {GRCH38_SYMBOLS/(decode_tput*N_CHIPS):.2f} s + "
        f"10 EM iters {EM_ITERS*EM_TRAIN_SYMBOLS/(em_tput*N_CHIPS):.2f} s)"
    )
    print(
        json.dumps(
            {
                "metric": "grch38_decode_plus_10em_projected_v5e8_seconds",
                "value": round(projected, 3),
                "unit": "s",
                "vs_baseline": round(TARGET_SECONDS / projected, 2),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
