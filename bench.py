"""Benchmark harness — prints ONE JSON line for the driver.

Metric: projected wall-clock for the north-star workload (BASELINE.json) on a
v5e-8 — Viterbi-decode all of GRCh38 (3.1 Gbp) AND run 10 Baum-Welch EM
iterations over a chr1-scale (250 Mbp) training set — assuming linear scaling
from the single measured chip to 8 chips (the sharded paths communicate only
[K,K]/[K] tensors per step — see the structural validation below, which
counts the compiled collectives and checks they are length-independent).

vs_baseline = 60 s / projected_s: the north star is "< 60 s on one v5e-8", so
vs_baseline > 1.0 means the target is beaten, and by how much.  (The reference
itself publishes no numbers — BASELINE.md — so the north star is the bar.)

Timing methodology: CHAINED — R iterations run inside one jit with a data
dependency between them (EM feeds params forward; decode perturbs one input
symbol from the previous path), one device sync at the end, wall / R.  This
measures steady-state on-chip throughput, which is what the workload sees on
real hardware (EM iterations and decode chunks run back-to-back).  Blocking
per-call timing is reported once to stderr for transparency: on this dev
setup each dispatch crosses a TPU relay with tens of ms of round-trip
latency, which per-call timing counts and production would not.

Usage: python bench.py [--decode-mib 256] [--em-chunks 512] [--engine auto]
       [--platform auto] [--extended]
(On CPU the decode size is capped at 16 MiB unless --decode-mib is given
explicitly — the 256 MiB default exists for TPU steady-state numbers.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

GRCH38_SYMBOLS = 3.1e9  # ~GRCh38 assembly length in bases
EM_TRAIN_SYMBOLS = 250e6  # chr1-scale training set (BASELINE.md config 2)
EM_ITERS = 10
TARGET_SECONDS = 60.0
N_CHIPS = 8  # v5e-8


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# No single-chip path on this hardware exceeds ~2.2 Gsym/s; anything past
# this outer net is a phantom result (see _best_wall), not a measurement.
# The value lives in cpgisland_tpu.obs.watchdog (the library generalization
# of this bench's plausibility discipline) — imported so there is ONE source.
# Importing the library here does not initialize any jax backend; --platform
# still takes effect in main() before first device use.
try:
    from cpgisland_tpu.obs.watchdog import PLAUSIBLE_MAX_SYM_PER_S
except Exception:  # degraded checkout: keep the bench self-sufficient
    PLAUSIBLE_MAX_SYM_PER_S = 20e9

# Per-path ceilings are much tighter (VERDICT r4 #6): 2.5x the enforced
# BASELINE.md figure for that metric, so a phantom that inflates one path
# 5x raises instead of sailing under the global net.  Parsed from the
# marker-wrapped BASELINE.md rows so they track the published numbers.
PATH_CEILING_FACTOR = 2.5
_PATH_CEILINGS: dict | None = None


def _baseline_key_by_path() -> dict:
    from cpgisland_tpu.obs import watchdog

    return watchdog.PATH_BASELINE_KEY


def _path_ceilings() -> dict:
    global _PATH_CEILINGS
    if _PATH_CEILINGS is None:
        # The marker parsing lives in cpgisland_tpu.obs.watchdog (the
        # library-wide plausibility watchdog this bench's checks graduated
        # into); tools/pubnum.py still owns the <!--num:key--> format and a
        # test pins the two regexes equal so they cannot drift.
        try:
            from cpgisland_tpu.obs import watchdog

            _PATH_CEILINGS = watchdog.path_ceilings(factor=PATH_CEILING_FACTOR)
        except Exception as e:
            # Degrade to the global net, don't sink the bench — but say so:
            # a capture artifact must record when per-path phantom defenses
            # were off (e.g. BASELINE.md missing/corrupt in the worktree).
            log(
                f"WARNING: per-path plausibility ceilings unavailable "
                f"({type(e).__name__}: {e}); only the global "
                f"{PLAUSIBLE_MAX_SYM_PER_S/1e9:.0f} Gsym/s net is enforced"
            )
            _PATH_CEILINGS = {}
    return _PATH_CEILINGS


def _check_plausible(tput: float, name: str) -> float:
    per_path = _path_ceilings().get(name, float("inf"))
    if tput > PLAUSIBLE_MAX_SYM_PER_S:
        raise RuntimeError(
            f"{name}: {tput/1e6:.1f} Msym/s exceeds the global plausibility "
            f"ceiling ({PLAUSIBLE_MAX_SYM_PER_S/1e6:.0f} Msym/s) — phantom "
            "relay result; re-run this phase in a fresh process"
        )
    if tput > per_path:
        # Distinguishable from the phantom case: a GENUINE speedup past
        # PATH_CEILING_FACTOR x the published figure lands here too, and the
        # fix for that is raising the BASELINE.md marker, not re-running.
        raise RuntimeError(
            f"{name}: {tput/1e6:.1f} Msym/s exceeds its per-path ceiling "
            f"({per_path/1e6:.0f} Msym/s = PATH_CEILING_FACTOR "
            f"{PATH_CEILING_FACTOR} x the enforced BASELINE.md "
            f"'{_baseline_key_by_path().get(name)}' figure). Either a phantom "
            "relay result (re-run this phase in a fresh process) or a real "
            ">2.5x improvement — if reproducible, update BASELINE.md via "
            "tools/pubnum.py --write from a fresh capture"
        )
    return tput


def armed_ceilings_record():
    """What this process actually enforces per path: ``{path: Msym/s}`` or
    the string ``"degraded-to-global"`` when the BASELINE.md markers failed
    to parse.  Every phase emits this into its JSON so a silent ceiling
    degradation is visible in the captured artifact instead of quietly
    widening the phantom net to the global 20 Gsym/s (VERDICT r5 #7)."""
    ceilings = _path_ceilings()
    if not ceilings:
        return "degraded-to-global"
    return {k: round(v / 1e6, 1) for k, v in sorted(ceilings.items())}


def _best_wall(fn, reps: int = 3) -> float:
    """Min wall-clock of fn(seed) over reps with DISTINCT seeds (fn must
    block internally and fold the seed into its input data).

    Byte-identical repeated executions have been observed coming back from
    the TPU relay in ~0 ms (a phantom result, not a measurement); a unique
    seed per rep makes every execution a distinct request.  Any rep under
    100 us is still treated as a phantom and retried with a fresh seed;
    persistent phantoms raise rather than publish a fantasy number.
    """
    best = float("inf")
    seed, done, phantoms = 1, 0, 0
    while done < reps:
        t0 = time.perf_counter()
        fn(seed)
        dt = time.perf_counter() - t0
        seed += 1
        if dt < 1e-4:
            phantoms += 1
            if phantoms > 4:
                raise RuntimeError(
                    f"persistent ~0 ms phantom timings ({dt*1e6:.0f} us rep)"
                )
            continue
        best = min(best, dt)
        done += 1
    return best


def bench_decode(
    n_symbols: int, engine: str = "auto", params=None, tag: str = "", chain: int = 6
) -> float:
    """Steady-state single-chip blockwise-parallel Viterbi throughput (sym/s).

    ``chain`` decodes run inside one jit, each perturbing its first symbol
    from the previous path (forces serialization, costs nothing), so
    per-dispatch latency is amortized away.
    """
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel
    from cpgisland_tpu.parallel.decode import resolve_engine

    if params is None:
        params = presets.durbin_cpg8()
    eng = resolve_engine(engine, params)
    rng = np.random.default_rng(0)
    obs = jnp.asarray(rng.integers(0, 4, size=n_symbols, dtype=np.int32))

    # obs MUST be a jit argument, not a closure capture: captured arrays are
    # baked into the program as literals, and on this setup the compile
    # payload ships over HTTP — a 256 MiB constant hits the body-size limit.
    @jax.jit
    def chained(c, obs):
        def body(c, _):
            path = viterbi_parallel(
                params, obs.at[0].set(c % 4), return_score=False, engine=eng
            )
            return jnp.min(path).astype(jnp.int32), None

        c, _ = jax.lax.scan(body, c, None, length=chain)
        return c

    jax.block_until_ready(chained(jnp.int32(0), obs))  # compile + warm
    # Timing FETCHES the scalar output: block_until_ready alone has been
    # observed returning without execution on the degraded relay (phantom
    # ~0 ms reps); a fetch cannot complete until the result exists.  Cost:
    # one extra RTT per rep, amortized over the chain.
    best = _best_wall(
        lambda s: int(jax.device_get(chained(jnp.int32(s), obs)))
    ) / chain
    tput = _check_plausible(n_symbols / best, f"decode{tag}")
    log(
        f"decode{tag}[{eng}]: {tput/1e6:.1f} Msym/s "
        f"({best*1e3:.0f} ms / {n_symbols/2**20:.0f} MiB, chained x{chain})"
    )
    return tput


def bench_em(
    n_chunks: int, chunk_size: int = 0x10000, engine: str = "auto", chain: int = 24
) -> float:
    """Steady-state single-chip E-step+M-step throughput (sym/s per EM iter).

    Default n_chunks=512 ~= the per-chip share of the chr1-scale EM workload on
    a v5e-8 (250e6 / 65536 / 8 chips ~= 477 chunks), so the measured batch is
    representative of what each chip actually processes.  ``chain`` EM
    iterations run back-to-back inside one jit, params feeding forward — the
    exact shape fit()'s loop produces on device.
    """
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.train.backends import LocalBackend, resolve_fb_engine
    from cpgisland_tpu.train.baum_welch import mstep

    params = presets.durbin_cpg8()
    eng = resolve_fb_engine(engine, params, "rescaled")
    backend = LocalBackend(mode="rescaled", engine=eng)
    rng = np.random.default_rng(1)
    chunks = jnp.asarray(
        rng.integers(0, 4, size=(n_chunks, chunk_size), dtype=np.int32).astype(np.uint8)
    )
    lengths = jnp.full(n_chunks, chunk_size, dtype=jnp.int32)

    @jax.jit
    def chained(p, chunks, lengths, s):
        chunks = chunks.at[0, 0].set((s % 4).astype(chunks.dtype))
        def body(p, _):
            return mstep(p, backend(p, chunks, lengths)), None

        p, _ = jax.lax.scan(body, p, None, length=chain)
        return p

    jax.block_until_ready(chained(params, chunks, lengths, jnp.int32(0)))
    best = _best_wall(
        lambda s: np.asarray(
            jax.device_get(chained(params, chunks, lengths, jnp.int32(s)).log_pi)
        ).sum()
    ) / chain

    # One blocking call for the latency-transparency line.
    @jax.jit
    def one(p, chunks, lengths):
        return mstep(p, backend(p, chunks, lengths))

    jax.block_until_ready(one(params, chunks, lengths))
    t0 = time.perf_counter()
    jax.block_until_ready(one(params, chunks, lengths))
    blocking = time.perf_counter() - t0

    n_sym = n_chunks * chunk_size
    tput = _check_plausible(n_sym / best, "em")
    log(
        f"em[{eng}]: {tput/1e6:.1f} Msym/s/iter ({best*1e3:.0f} ms / "
        f"{n_sym/2**20:.0f} MiB, chained x{chain}; blocking single call "
        f"{blocking*1e3:.0f} ms incl. dispatch latency)"
    )
    return tput


def bench_batched_decode(
    n_seqs: int, seq_len: int, engine: str = "auto", chain: int = 6
) -> float:
    """Batched multi-genome decode throughput in sym/s (BASELINE.md config
    5): N independent sequences decoded as one [N, T] batch — the onehot
    engine runs them as ONE flat stream with record-reset steps
    (viterbi_onehot.decode_batch_flat); dense engines vmap."""
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel_batch
    from cpgisland_tpu.parallel.decode import resolve_engine

    params = presets.durbin_cpg8()
    eng = resolve_engine(engine, params)
    rng = np.random.default_rng(2)
    chunks = jnp.asarray(rng.integers(0, 4, size=(n_seqs, seq_len), dtype=np.int32))
    lengths = jnp.full(n_seqs, seq_len, dtype=jnp.int32)

    @jax.jit
    def chained(c, chunks, lengths):
        def body(c, _):
            paths = viterbi_parallel_batch(
                params, chunks.at[0, 0].set(c % 4), lengths, return_score=False, engine=eng
            )
            return jnp.min(paths).astype(jnp.int32), None

        c, _ = jax.lax.scan(body, c, None, length=chain)
        return c

    jax.block_until_ready(chained(jnp.int32(0), chunks, lengths))
    best = _best_wall(
        lambda s: int(jax.device_get(chained(jnp.int32(s), chunks, lengths)))
    ) / chain
    n_sym = n_seqs * seq_len
    tput = _check_plausible(n_sym / best, "batched-decode")
    log(
        f"batched-decode[{eng}]: {tput/1e6:.1f} Msym/s "
        f"({n_seqs} x {seq_len/2**20:.0f} MiB in {best*1e3:.0f} ms, chained x{chain})"
    )
    return tput


def bench_posterior(n_symbols: int, engine: str = "auto", chain: int = 6) -> float:
    """Steady-state posterior (soft) decoding throughput in sym/s: per-position
    island confidence through the lane-parallel FB machinery (VERDICT r2 #1 —
    the soft path must ride the same kernels as the hard decode).

    Pallas engine: the fused single-device core.  XLA engine (CPU runs): the
    blockwise lane path sharded over every local device.
    """
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.parallel.posterior import resolve_fb_engine

    params = presets.durbin_cpg8()
    eng = resolve_fb_engine(engine, params)
    rng = np.random.default_rng(5)
    obs = jnp.asarray(rng.integers(0, 4, size=n_symbols, dtype=np.int32).astype(np.uint8))
    mask = jnp.asarray((np.arange(params.n_states) < params.n_symbols).astype(np.float32))

    if eng in ("pallas", "onehot"):
        from cpgisland_tpu.ops import fb_pallas

        def one(o):
            conf, _ = fb_pallas._seq_posterior_core(
                params, o, o.shape[0], mask,
                fb_pallas.pick_lane_T(
                    o.shape[0], onehot=eng == "onehot",
                    long_lanes=eng == "onehot",
                ),
                fb_pallas.DEFAULT_T_TILE,
                axis=None, onehot=eng == "onehot",
            )
            return conf
    else:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from cpgisland_tpu.parallel.fb_sharded import _one_seq_local_posterior
        from cpgisland_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(len(jax.devices()), axis="seq")
        axis = mesh.axis_names[0]

        def body(p, o):
            return _one_seq_local_posterior(
                p, o, jnp.int32(o.shape[0]), mask, axis=axis, block_size=1024
            )[0]

        smap = jax.shard_map(
            body, mesh=mesh, in_specs=(P(), P(axis)), out_specs=P(axis)
        )
        obs = jax.device_put(obs, NamedSharding(mesh, P(axis)))

        def one(o):
            return smap(params, o)

    @jax.jit
    def chained(c, obs):
        def step(c, _):
            conf = one(obs.at[0].set((c % 4).astype(obs.dtype)))
            return (jnp.min(conf) * 4.0).astype(jnp.int32) % 4, None

        c, _ = jax.lax.scan(step, c, None, length=chain)
        return c

    jax.block_until_ready(chained(jnp.int32(0), obs))  # compile + warm
    best = _best_wall(
        lambda s: int(jax.device_get(chained(jnp.int32(s), obs)))
    ) / chain
    tput = _check_plausible(n_symbols / best, "posterior")
    log(
        f"posterior[{eng}]: {tput/1e6:.1f} Msym/s "
        f"({best*1e3:.0f} ms / {n_symbols/2**20:.0f} MiB, chained x{chain})"
    )
    return tput


def bench_em_2state(n_chunks: int, chunk_size: int = 0x10000, chain: int = 24) -> float:
    """2-state model EM throughput in sym/s/iter (BASELINE.md config 2)."""
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.train.backends import LocalBackend, resolve_fb_engine
    from cpgisland_tpu.train.baum_welch import mstep

    params = presets.two_state_cpg()
    # auto resolves to the Pallas E-step kernels on TPU (they handle any
    # n_states <= 8, not just the flagship 8-state shape).
    eng = resolve_fb_engine("auto", params, "rescaled")
    backend = LocalBackend(mode="rescaled", engine=eng)
    rng = np.random.default_rng(3)
    chunks = jnp.asarray(
        rng.integers(0, 4, size=(n_chunks, chunk_size), dtype=np.int32).astype(np.uint8)
    )
    lengths = jnp.full(n_chunks, chunk_size, dtype=jnp.int32)

    @jax.jit
    def chained(p, chunks, lengths, s):
        chunks = chunks.at[0, 0].set((s % 4).astype(chunks.dtype))
        def body(p, _):
            return mstep(p, backend(p, chunks, lengths)), None

        p, _ = jax.lax.scan(body, p, None, length=chain)
        return p

    jax.block_until_ready(chained(params, chunks, lengths, jnp.int32(0)))
    best = _best_wall(
        lambda s: np.asarray(
            jax.device_get(chained(params, chunks, lengths, jnp.int32(s)).log_pi)
        ).sum()
    ) / chain
    tput = _check_plausible(n_chunks * chunk_size / best, "em-2state")
    log(f"em-2state[{eng}]: {tput/1e6:.1f} Msym/s/iter ({best*1e3:.0f} ms, chained x{chain})")
    return tput


def bench_em_fused_dispatches(n_chunks: int = 16, iters: int = 10) -> dict:
    """Fused-vs-host EM blocking-dispatch counts via the obs ledger.

    NOT a throughput figure: this certifies the latency-hiding contract —
    ``iters`` steady-state fused iterations compile once and pay <= 2
    blocking dispatches (one result fetch), where the host loop pays 2 per
    iteration (the delta + loglik syncs).  Every blocking call on the relay
    is a ~50-100 ms round trip, so this count IS the latency story.  The
    chunk batch is pre-placed as device arrays so the measured region is
    the loop cadence, not the one-time upload.
    """
    import jax.numpy as jnp

    from cpgisland_tpu import obs as obs_mod
    from cpgisland_tpu.models import presets
    from cpgisland_tpu.train import baum_welch
    from cpgisland_tpu.utils import chunking

    params = presets.durbin_cpg8()
    rng = np.random.default_rng(11)
    raw = chunking.frame(
        rng.integers(0, 4, size=n_chunks * 0x10000).astype(np.uint8), 0x10000
    )
    ck = chunking.Chunked(
        chunks=jnp.asarray(raw.chunks), lengths=jnp.asarray(raw.lengths),
        total=raw.total,
    )

    def fit(fuse):
        return baum_welch.fit(
            params, ck, num_iters=iters, convergence=0.0, fuse=fuse
        )

    fit(True)  # warm the fused program
    fit(False)  # warm the per-iteration programs
    # A full Observer (not a bare ledger install): the host loop's
    # per-iteration sync is counted through the obs.note_fetch piggyback,
    # which only routes when an observer is active.  Reuse the
    # --metrics-out observer when one is already installed (no nesting).
    import contextlib

    ob = obs_mod.current()
    ctx = contextlib.nullcontext(ob) if ob is not None else obs_mod.observe()
    with ctx as obx:
        led = obx.ledger
        snap = led.snapshot()
        fit(True)
        d_fused = led.delta(snap)
        snap = led.snapshot()
        fit(False)
        d_host = led.delta(snap)
    out = {
        "iters": iters,
        "fused_dispatches": d_fused["dispatches"],
        "fused_steady_compiles": d_fused["compiles"],
        "host_dispatches": d_host["dispatches"],
    }
    log(
        f"em-fused: {iters} steady-state iters = {out['fused_dispatches']} "
        f"blocking dispatch(es), {out['fused_steady_compiles']} fresh "
        f"compile(s) (host loop: {out['host_dispatches']} dispatches)"
    )
    return out


def _seq_engine_for_bench(engine: str, params, shard_len: int) -> str:
    """Pre-resolve the seq-backend engine with CONCRETE params.

    The chained harness calls the backend INSIDE one jit, where its auto
    routing sees traced params and cannot run the one-hot eligibility check
    (a concrete-params structural test).  Real training (fit()) routes per
    iteration in Python with concrete params and DOES auto-select the
    reduced kernels — so the bench pre-resolves here to measure what real
    training runs, keeping auto's own fused-path gate (shard >= 1 Mi, see
    backends._use_fused_seq) so small configs still measure the route real
    auto training would take."""
    import jax

    if engine != "auto" or jax.default_backend() != "tpu" or shard_len < (1 << 20):
        return engine
    from cpgisland_tpu.ops import fb_onehot

    return "onehot" if fb_onehot.supports(params) else engine


def bench_em_seq(n_symbols: int, engine: str = "auto", chain: int = 8) -> float:
    """EXACT whole-sequence EM throughput (sym/s per iter) — the flagship
    beyond-the-reference training capability (SeqBackend: no 64 Ki
    chunk-independence approximation).  Chained like the other configs:
    ``chain`` iterations in one jit, params feeding forward through the
    M-step, so the figure is steady-state on-chip rate (VERDICT r3 #3 — this
    number was previously only a code comment)."""
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.parallel.mesh import make_mesh
    from cpgisland_tpu.train.backends import SeqBackend
    from cpgisland_tpu.train.baum_welch import mstep
    from cpgisland_tpu.utils import chunking

    params = presets.durbin_cpg8()
    backend = SeqBackend(
        mesh=make_mesh(len(jax.devices()), axis="seq"),
        engine=_seq_engine_for_bench(
            engine, params, n_symbols // len(jax.devices())
        ),
    )
    rng = np.random.default_rng(6)
    stream = rng.integers(0, 4, size=n_symbols, dtype=np.int32).astype(np.uint8)
    prepared = backend.prepare(
        chunking.Chunked(
            chunks=stream[None, :], lengths=np.asarray([n_symbols], np.int32),
            total=n_symbols,
        )
    )
    obs, lens = backend.place(prepared.chunks, prepared.lengths)

    @jax.jit
    def chained(p, obs, lens, s):
        obs = obs.at[0].set((s % 4).astype(obs.dtype))
        def body(p, _):
            return mstep(p, backend(p, obs, lens)), None

        p, _ = jax.lax.scan(body, p, None, length=chain)
        return p

    jax.block_until_ready(chained(params, obs, lens, jnp.int32(0)))
    best = _best_wall(
        lambda s: np.asarray(
            jax.device_get(chained(params, obs, lens, jnp.int32(s)).log_pi)
        ).sum()
    ) / chain
    tput = _check_plausible(n_symbols / best, "em-seq")
    log(
        f"em-seq[{backend.engine}]: {tput/1e6:.1f} Msym/s/iter "
        f"({best*1e3:.0f} ms / {n_symbols/2**20:.0f} MiB whole-sequence, "
        f"chained x{chain})"
    )
    return tput


def bench_em_seq2d(engine: str = "auto", chain: int = 8, scale: float = 1.0) -> float:
    """EXACT bucketed per-record EM throughput (sym/s per iter): a
    chromosome-plus-scaffolds shaped input through Seq2DBackend's per-group
    dp x sp meshes.  Each group's stats fn is chained separately (groups run
    back-to-back on device in a real iteration; chaining amortizes the relay
    dispatch exactly like every other config) and the iteration time is the
    sum over groups."""
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.train.backends import Seq2DBackend
    from cpgisland_tpu.train.baum_welch import mstep
    from cpgisland_tpu.utils import chunking

    params = presets.durbin_cpg8()
    # Gate on the SMALLEST group's row length — auto routes per group.
    backend = Seq2DBackend(
        engine=_seq_engine_for_bench(engine, params, int((2 << 20) * scale))
    )
    rng = np.random.default_rng(8)
    # One "chromosome" group + one scaffold group (pow2 size classes, like
    # chunking.bucket_records builds): 32 Mi + 8 x 2 Mi at scale=1.
    groups = [(1, int((32 << 20) * scale)), (8, int((2 << 20) * scale))]
    chunks_t, lens_t = [], []
    for rows, ln in groups:
        chunks_t.append(
            rng.integers(0, 4, size=(rows, ln), dtype=np.int32).astype(np.uint8)
        )
        lens_t.append(np.full(rows, ln, np.int32))
    total = sum(r * ln for r, ln in groups)
    bucketed = chunking.Bucketed(
        chunks=tuple(chunks_t), lengths=tuple(lens_t), total=total
    )
    prepared = backend.prepare(bucketed)
    obs_t, len_t = backend.place(prepared.chunks, prepared.lengths)

    per_iter = 0.0
    for g, (mesh_g, obs, lens) in enumerate(
        zip(backend._group_meshes, obs_t, len_t)
    ):
        @jax.jit
        def chained(p, obs, lens, s):
            obs = obs.at[0, 0].set((s % 4).astype(obs.dtype))
            def body(p, _):
                return mstep(p, backend._group_stats(p, mesh_g, obs, lens)), None

            p, _ = jax.lax.scan(body, p, None, length=chain)
            return p

        jax.block_until_ready(chained(params, obs, lens, jnp.int32(0)))
        per_iter += _best_wall(
            lambda s, c=chained, o=obs, l=lens: np.asarray(
                jax.device_get(c(params, o, l, jnp.int32(s)).log_pi)
            ).sum()
        ) / chain
    tput = _check_plausible(total / per_iter, "em-seq2d")
    log(
        f"em-seq2d[{backend.engine}]: {tput/1e6:.1f} Msym/s/iter "
        f"({per_iter*1e3:.0f} ms / {total/2**20:.0f} MiB in {len(groups)} "
        f"bucket groups, chained x{chain})"
    )
    return tput


def _planted_record(n: int, boundary: int, rng) -> np.ndarray:
    """AT-rich DNA (the e2e bench's human-like composition — uniform ACGT
    is 50% GC and decodes to ~500k spurious micro-islands at 320 Mi) with a
    strong CG island straddling ``boundary`` and a few elsewhere, as
    symbols — for the span-continuity configs."""
    obs = rng.choice(
        np.arange(4, dtype=np.uint8), size=n, p=[0.32, 0.18, 0.18, 0.32]
    )
    spots = [boundary - 2000] + [
        int(x) for x in rng.integers(0, n - 4000, size=8)
    ]
    cg = rng.choice(np.array([1, 2], np.uint8), size=4000)
    for lo in spots:
        obs[lo : lo + 4000] = cg[: max(0, min(4000, n - lo))]
    return obs


def bench_span_decode(n_symbols: int, span: int, engine: str = "auto") -> dict:
    """Span-threaded EXACT decode at beyond-one-pass scale (VERDICT r3 #2):
    one record larger than the decode span runs viterbi_sharded_spans (>= 2
    spans with boundary messages threaded), device island calling included,
    with a planted island straddling the span boundary asserted to come out
    WHOLE.  Wall-clock includes the real host-side span threading — the
    overhead the span constants' memory budgets trade against."""
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.ops.islands_device import call_islands_device
    from cpgisland_tpu.parallel.decode import viterbi_sharded_spans

    params = presets.durbin_cpg8()
    rng = np.random.default_rng(9)
    obs = _planted_record(n_symbols, span, rng)
    n_spans = -(-n_symbols // span)
    assert n_spans >= 2, "config must force the span path"

    def run():
        # Decode AND device island calling inside the timed window — the
        # published row claims the full decode->islands span pipeline.
        pieces = viterbi_sharded_spans(
            params, obs, span=span, engine=engine, return_device=True
        )
        full = pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)
        return call_islands_device(full)

    def run_single():
        # The SAME user path at one-pass scale: a span-sized prefix decoded
        # in one span + device island call.  Its per-symbol wall is the
        # denominator of the span-overhead ratio — both runs pay the same
        # relay upload per byte (the dominant cost on this dev setup), so
        # the ratio isolates the true span-threading overhead robustly.
        pieces = viterbi_sharded_spans(
            params, obs[:span], span=span, engine=engine, return_device=True
        )
        return call_islands_device(pieces[0])

    run()  # compile + warm (spans share one padded shape)
    run_single()  # warm the one-pass shapes too (distinct compiled fns)
    # One-symbol perturbation: the measured pass must not be byte-identical
    # to the warm pass (the relay can phantom-serve repeated requests).
    obs[0] = (obs[0] + 1) % 4
    t0 = time.perf_counter()
    calls = run()
    wall = time.perf_counter() - t0
    obs[0] = (obs[0] + 1) % 4
    t0 = time.perf_counter()
    run_single()
    wall1 = time.perf_counter() - t0
    tput = n_symbols / wall
    overhead = (wall / n_symbols) / (wall1 / span)
    crossing = [
        (b, e) for b, e in zip(calls.beg, calls.end) if b <= span < e
    ]
    assert crossing, (
        f"no island call crosses the span boundary at {span} — continuity "
        f"machinery not exercised ({len(calls)} calls)"
    )
    mem = _device_memory_gb()
    stats = {
        "span_decode_msym_per_s": round(tput / 1e6, 1),
        "span_decode_overhead": round(overhead, 2),
        "n_spans": n_spans,
        "n_islands": len(calls),
        "boundary_island": [int(crossing[0][0]), int(crossing[0][1])],
        **mem,
    }
    log(
        f"span-decode[{engine}]: {tput/1e6:.1f} Msym/s user-path wall "
        f"({wall:.2f}s for a {n_symbols/2**20:.0f} MiB record in {n_spans} "
        f"spans of {span/2**20:.0f} MiB incl. host boundary threading; "
        f"{overhead:.2f}x the per-symbol wall of the one-pass user path at "
        f"{span/2**20:.0f} MiB, which pays the same per-byte input upload — "
        f"upload-bound on this relayed dev setup, compute-bound on PCIe; "
        f"cross-boundary island {crossing[0][0]}-{crossing[0][1]} emitted "
        f"whole) " + json.dumps(mem)
    )
    return stats


def bench_span_posterior(n_symbols: int, span: int, engine: str = "auto") -> dict:
    """Span-threaded EXACT posterior at beyond-one-pass scale through the
    REAL user path — pipeline.posterior_file in island-only device mode (no
    per-symbol outputs; VERDICT r3 #2 + #4 together): enter/exit directions
    threaded between >= 2 POSTERIOR_SPAN spans, islands called over the
    whole record's device-resident MPM path."""
    import jax

    from cpgisland_tpu import pipeline
    from cpgisland_tpu.models import presets
    from cpgisland_tpu.utils import profiling

    params = presets.durbin_cpg8()
    rng = np.random.default_rng(10)
    obs = _planted_record(n_symbols, span, rng)
    n_spans = -(-n_symbols // span)
    assert n_spans >= 2
    tmpdir = tempfile.mkdtemp(prefix="cpg_span_")
    fa = os.path.join(tmpdir, "span.fa")
    acgt = np.frombuffer(b"acgt", np.uint8)
    text = acgt[obs]
    with open(fa, "wb") as f:
        f.write(b">spanrec\n")
        rows = text[: (n_symbols // 80) * 80].reshape(-1, 80)
        f.write(b"\n".join(bytes(r) for r in rows) + b"\n")
    out = os.path.join(tmpdir, "islands.txt")
    island_engine = "device" if jax.default_backend() == "tpu" else "auto"

    def run(tag):
        timer = profiling.PhaseTimer()
        t0 = time.perf_counter()
        res = pipeline.posterior_file(
            fa, params, islands_out=out, engine=engine,
            island_engine=island_engine, span=span, timer=timer,
        )
        return time.perf_counter() - t0, res, timer

    # A one-pass twin at span size through the SAME user path (single
    # record of ``span`` symbols): per-symbol wall denominator for the
    # span-overhead ratio (both pay the same per-byte upload + parse).
    fa1 = os.path.join(tmpdir, "single.fa")
    with open(fa1, "wb") as f:
        f.write(b">single\n")
        rows1 = text[: (span // 80) * 80].reshape(-1, 80)
        f.write(b"\n".join(bytes(r) for r in rows1) + b"\n")

    def run_single():
        t0 = time.perf_counter()
        pipeline.posterior_file(
            fa1, params, islands_out=out, engine=engine,
            island_engine=island_engine, span=span,
        )
        return time.perf_counter() - t0

    run("warm")  # compiles (spans share one padded shape)
    run_single()  # warm the single-span shapes (same compiled fns)
    # De-duplicate the measured pass from the warm pass (phantom guard).
    with open(fa, "r+b") as f:
        f.seek(len(">spanrec\n"))
        f.write(b"t")
    with open(fa1, "r+b") as f:
        f.seek(len(">single\n"))
        f.write(b"t")
    wall, res, timer = run("measured")
    wall1 = run_single()
    dev_s = sum(ph.seconds for ph in timer.phases.values())
    tput = n_symbols / wall
    overhead = (wall / n_symbols) / (wall1 / span)
    crossing = [
        (b, e) for b, e in zip(res.calls.beg, res.calls.end) if b <= span < e
    ]
    assert crossing, "no island crosses the posterior span boundary"
    mem = _device_memory_gb()
    for p in (fa, fa1, out):
        os.unlink(p)
    os.rmdir(tmpdir)
    stats = {
        "span_posterior_msym_per_s": round(tput / 1e6, 1),
        "span_posterior_overhead": round(overhead, 2),
        "n_spans": n_spans,
        "n_islands": len(res.calls),
        **mem,
    }
    log(
        f"span-posterior[{engine}]: {tput/1e6:.1f} Msym/s user-path wall "
        f"({wall:.2f}s end-to-end incl. FASTA parse for a "
        f"{n_symbols/2**20:.0f} MiB record in {n_spans} spans of "
        f"{span/2**20:.0f} MiB, island-only device mode, device phases "
        f"{dev_s:.2f}s; {overhead:.2f}x the per-symbol wall of the one-pass "
        f"user path at {span/2**20:.0f} MiB — upload-bound on this relayed "
        f"dev setup, compute-bound on PCIe; cross-boundary island "
        f"{crossing[0][0]}-{crossing[0][1]} emitted whole) " + json.dumps(mem)
    )
    return stats


def _device_memory_gb() -> dict:
    """Peak/in-use HBM if the backend exposes it (guarded: the relay plugin
    may not) — the span configs exist to validate the span constants'
    device-memory budgets, so report the headroom when we can see it."""
    import jax

    try:
        ms = jax.devices()[0].memory_stats() or {}
        out = {}
        if "peak_bytes_in_use" in ms:
            out["peak_hbm_gb"] = round(ms["peak_bytes_in_use"] / 2**30, 2)
        if "bytes_limit" in ms:
            out["hbm_limit_gb"] = round(ms["bytes_limit"] / 2**30, 2)
        return out
    except Exception:
        return {}


def bench_end_to_end(n_mbases: int, engine: str = "auto") -> dict:
    """The full reference ``testModel`` scope, measured for real: FASTA file on
    disk -> host encode -> device decode -> host island calls -> records
    written (CpGIslandFinder.java:227-344).  Returns phase throughputs so
    BASELINE.md can state whether the host keeps up with 8-chip decode.
    """
    from cpgisland_tpu import pipeline
    from cpgisland_tpu.models import presets
    from cpgisland_tpu.utils import profiling

    rng = np.random.default_rng(7)
    n = n_mbases * 1_000_000
    # ~CpG-realistic composition: ~1 kb GC-rich islands embedded every ~50 kb
    # in AT-rich background (approximating human island density), so the
    # island caller does representative work rather than fuzz on noise.
    acgt = np.frombuffer(b"acgt", np.uint8)
    bg = rng.choice(acgt, size=n, p=[0.32, 0.18, 0.18, 0.32])
    n_islands = max(1, n // 50_000)
    locs = rng.integers(0, max(1, n - 2000), size=n_islands)
    for lo in locs:
        ln = int(rng.integers(500, 1800))
        bg[lo : lo + ln] = rng.choice(acgt, size=min(ln, n - lo), p=[0.08, 0.42, 0.42, 0.08])
    tmpdir = tempfile.mkdtemp(prefix="cpg_bench_")
    fa = os.path.join(tmpdir, "bench.fa")
    with open(fa, "wb") as f:
        f.write(b">bench\n")
        rows = bg[: (n // 80) * 80].reshape(-1, 80)
        f.write(b"\n".join(bytes(r) for r in rows) + b"\n")
    out = os.path.join(tmpdir, "islands.txt")

    # Host-side encode rate, measured standalone (clean-mode decode_file
    # streams records internally without a separate encode phase timer) —
    # plus the symbol-cache repeat-run path (VERDICT r2 #4: the named fix
    # for the encode bottleneck), measured as a warm second read.
    from cpgisland_tpu.utils import codec

    t0 = time.perf_counter()
    enc_syms = sum(s.size for _, s in codec.iter_fasta_records(fa))
    encode_s = time.perf_counter() - t0
    cache_prefix = fa  # sidecar files in the bench tmpdir
    codec.write_symbol_cache(fa, cache_prefix)
    t0 = time.perf_counter()
    cached_total = 0
    for _, s in codec.iter_fasta_records_cached(fa, cache_prefix):
        # Touch the bytes (sum) so the memmap pages actually stream.
        cached_total += s.size + int(np.asarray(s).sum(dtype=np.int64)) * 0
    cached_s = time.perf_counter() - t0
    assert cached_total == enc_syms

    # Steady state: first pass pays jit compiles (one per record shape — real
    # workloads reuse the fixed 256 Mi span shape), second pass is measured.
    pipeline.decode_file(
        fa, presets.durbin_cpg8(), islands_out=out, compat=False, engine=engine
    )
    with open(fa, "r+b") as f:  # de-dup the measured pass (phantom guard)
        f.seek(len(">bench\n"))
        f.write(b"t")
    timer = profiling.PhaseTimer()
    t0 = time.perf_counter()
    res = pipeline.decode_file(
        fa,
        presets.durbin_cpg8(),
        islands_out=out,
        compat=False,
        engine=engine,
        timer=timer,
    )
    wall = time.perf_counter() - t0
    stats = {
        "file_mbases": n_mbases,
        "end_to_end_s": round(wall, 3),
        "end_to_end_msym_per_s": round(res.n_symbols / wall / 1e6, 1),
        "encode_msym_per_s": round(enc_syms / max(encode_s, 1e-9) / 1e6, 1),
        "cached_encode_msym_per_s": round(
            enc_syms / max(cached_s, 1e-9) / 1e6, 1
        ),
        "n_islands": len(res.calls),
    }
    for name, ph in timer.phases.items():
        stats[f"{name.replace('+', '_')}_msym_per_s"] = round(
            ph.items / max(ph.seconds, 1e-9) / 1e6, 1
        )
    for p in (fa, out, *codec.symbol_cache_paths(cache_prefix)):
        os.unlink(p)
    os.rmdir(tmpdir)
    log(f"end-to-end ({n_mbases} Mbase file): " + json.dumps(stats))
    return stats


def _achieved_score(params, obs: np.ndarray, path: np.ndarray) -> float:
    """f64 host re-scoring of a decoded path (no PADs in bench inputs):
    log pi(s0) + log B(s0,o0) + sum_t log A(s_{t-1},s_t) + log B(s_t,o_t)."""
    lp = np.asarray(params.log_pi, np.float64)
    lA = np.asarray(params.log_A, np.float64)
    lB = np.asarray(params.log_B, np.float64)
    s = lp[path[0]] + lB[path[0], obs[0]]
    return float(s + (lA[path[:-1], path[1:]] + lB[path[1:], obs[1:]]).sum())


def bench_parity(n_mib: int = 4) -> dict:
    """On-chip dense-vs-reduced certification gate (VERDICT r4 #1a).

    The reduced onehot kernels are TPU-only lowerings: the CPU suite runs
    their XLA scan twins, so until this gate the numbers captured on the
    chip were produced by kernels whose on-chip correctness no artifact
    attested.  This phase runs BOTH lowerings on the same few-MiB inputs on
    whatever backend the bench runs on and asserts:

    - decode: exact path equality on a tie-free one-hot model, and on the
      flagship Durbin model the pinned tie contract (scores to ~1e-6
      relative; any path mismatch must re-score f64-identically — ties);
    - posterior: island-confidence allclose (atol 5e-5);
    - EM: chunked E-step SuffStats and the whole-sequence (z-normalized)
      stats kernel allclose against the dense kernels.

    Raises on any violation (the orchestrator records only clean passes);
    returns the measured deltas for the captured artifact.
    """
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.models.hmm import HmmParams
    from cpgisland_tpu.ops import fb_pallas
    from cpgisland_tpu.ops.viterbi_parallel import viterbi_parallel
    from cpgisland_tpu.train.backends import LocalBackend

    on_tpu = jax.default_backend() == "tpu"
    # Off-TPU the dense DECODE twin is the XLA engine: the Pallas viterbi
    # kernels' select-derived backpointer chains are pathologically slow
    # under the interpreter (CLAUDE.md).  The dense FB cores below
    # (conf(False)/seq_stats(False)) DO run interpreted off-TPU — measured
    # tolerable (~1 min total at the 1 MiB CPU gate size), and on TPU
    # (where this gate matters) everything runs the real kernels.
    dense_dec, dense_fb = ("pallas", "pallas") if on_tpu else ("xla", "xla")
    n = n_mib << 20
    rng = np.random.default_rng(11)
    obs = rng.integers(0, 4, size=n, dtype=np.int32)
    obs_j = jnp.asarray(obs)
    out: dict = {"n_mib": n_mib, "backend": jax.default_backend()}

    # --- decode, tie-free model: paths must be EXACTLY equal.
    pi8 = rng.dirichlet(np.ones(8))
    A8 = rng.dirichlet(np.ones(8), size=8)
    A8 = A8 * np.exp(rng.normal(scale=1e-3, size=A8.shape))  # break ties
    A8 = A8 / A8.sum(axis=1, keepdims=True)
    B8 = np.zeros((8, 4))
    B8[np.arange(8), np.arange(8) % 4] = 1.0
    tie_free = HmmParams.from_probs(pi8, A8, B8)

    def paths(params, eng, o_dev):
        fn = jax.jit(
            lambda o: viterbi_parallel(params, o, return_score=True, engine=eng)
        )
        path, score = fn(o_dev)
        return np.asarray(path), float(score)

    def check_decode(params, what, o_dev=None, o_host=None, dense=None):
        """The pinned engine contract (PARITY.md C10): scores to ~1e-6
        relative, and any path mismatch must be a rounding tie — both paths
        re-score f64-identically.  (Even the perturbed tie-free model can
        produce f32 NEAR-ties at the ~1e-7 normalizer-rounding level on
        multi-Mi inputs, so the tie escape applies to both models — a
        deterministic benign tie must not abort the whole capture.)
        ``o_dev``/``o_host`` default to the base stream; the family member
        passes its pair-recoded twin.  ``dense`` overrides the dense
        baseline engine (models outside the pallas packing envelope must
        compare against XLA on every backend)."""
        o_dev = obs_j if o_dev is None else o_dev
        o_host = obs if o_host is None else o_host
        p_d, s_d = paths(params, dense_dec if dense is None else dense, o_dev)
        p_o, s_o = paths(params, "onehot", o_dev)
        rel = abs(s_o - s_d) / max(abs(s_d), 1.0)
        mism = int((p_d != p_o).sum())
        if rel > 2e-6:
            raise AssertionError(f"parity-gate decode({what}): score rel {rel:.2e}")
        if mism:
            a_d = _achieved_score(params, o_host, p_d)
            a_o = _achieved_score(params, o_host, p_o)
            if abs(a_d - a_o) > 1e-6 * abs(a_d):
                raise AssertionError(
                    f"parity-gate decode({what}): {mism} mismatches NOT ties "
                    f"(f64 re-scores {a_d:.6f} vs {a_o:.6f})"
                )
        out[f"decode_{what}_mismatches"] = mism
        out[f"decode_{what}_score_reldiff"] = rel

    check_decode(tie_free, "tiefree")

    # --- decode, flagship model (the one the published numbers run).
    flag = presets.durbin_cpg8()
    check_decode(flag, "flagship")

    # --- decode, the order-2 FAMILY member (dinucleotide model over the
    # pair alphabet): the family generalization's reduced lowering (16
    # blocks of 2, family.partition_of) certified on the same silicon.
    from cpgisland_tpu.utils import codec as _codec

    # Dense baseline pinned to XLA on EVERY backend: K=32 exceeds the
    # pallas engine's 3-bit backpointer packing (viterbi_pallas.supports),
    # so the TPU default of dense_dec='pallas' would compare against a
    # silently-corrupt path.
    obs_pair = _codec.recode_pairs(obs.astype(np.uint8), prev=0).astype(np.int32)
    check_decode(
        presets.dinuc_cpg(), "dinuc", jnp.asarray(obs_pair), obs_pair,
        dense="xla",
    )

    # --- posterior confidence.
    mask = jnp.asarray((np.arange(8) < 4).astype(np.float32))
    obs_u8 = jnp.asarray(obs[: n // 2].astype(np.uint8))

    def conf(onehot):
        lt = fb_pallas.pick_lane_T(
            obs_u8.shape[0], onehot=onehot, long_lanes=onehot
        )
        fn = jax.jit(
            lambda o: fb_pallas._seq_posterior_core(
                flag, o, o.shape[0], mask, lt, fb_pallas.DEFAULT_T_TILE,
                axis=None, onehot=onehot,
            )[0]
        )
        return np.asarray(fn(obs_u8))

    c_d = conf(False)
    c_o = conf(True)
    conf_max = float(np.abs(c_d - c_o).max())
    if conf_max > 5e-5:
        raise AssertionError(f"parity-gate posterior: max conf diff {conf_max:.2e}")
    out["posterior_conf_maxdiff"] = conf_max

    # --- EM chunked E-step stats.
    n_chunks = 64 if on_tpu else 8
    chunks = jnp.asarray(
        rng.integers(0, 4, size=(n_chunks, 0x10000), dtype=np.int32).astype(np.uint8)
    )
    lengths = jnp.full(n_chunks, 0x10000, dtype=jnp.int32)

    def em_stats(eng):
        backend = LocalBackend(mode="rescaled", engine=eng)
        st = jax.jit(lambda c, l: backend(flag, c, l))(chunks, lengths)
        return jax.tree_util.tree_map(np.asarray, st)

    st_d = em_stats(dense_fb)
    st_o = em_stats("onehot")
    out["em_stats_maxrel"] = _stats_maxrel(st_d, st_o, "em chunked")

    # --- EXACT whole-sequence stats (the z-normalized kernel path).
    # Reuses the posterior section's device-resident array: the relay's
    # host->device upload is slow enough that a duplicate upload matters.
    seq_obs = obs_u8

    def seq_stats(onehot):
        lt = fb_pallas.pick_lane_T(
            seq_obs.shape[0], onehot=onehot, long_lanes=onehot
        )
        st = jax.jit(
            lambda o: fb_pallas.seq_stats_pallas(
                flag, o, o.shape[0], lane_T=lt, onehot=onehot
            )
        )(seq_obs)
        return jax.tree_util.tree_map(np.asarray, st)

    if on_tpu or fb_pallas.supports(flag):
        sq_d = seq_stats(False)
        sq_o = seq_stats(True)
        out["em_seq_stats_maxrel"] = _stats_maxrel(sq_d, sq_o, "em seq")

    # --- jaxpr contracts on the capturing backend (graftcheck layer 2,
    # LINT.md): engine routing + graph hygiene certified on the same
    # silicon as the published numbers — on TPU this additionally asserts
    # the reduced kernels actually ENGAGE (pallas_call present in the
    # traced graphs).  Trace-only here: the stability executions would pay
    # relay round trips the numeric parity sections above already cover.
    from cpgisland_tpu.analysis import contracts as graft_contracts

    cres = graft_contracts.run_contracts(execute=False)
    csum = graft_contracts.summarize(cres)
    if not csum["ok"]:
        raise AssertionError(f"parity-gate contracts: {csum['violations']}")
    out["contracts"] = {
        "checked": csum["checked"],
        "pallas_engaged": {
            r.name: r.notes["pallas_calls"]
            for r in cres
            if r.notes.get("pallas_calls")
        },
    }

    # --- graftcheck layer 3 on the capturing backend: diff live cost
    # fingerprints against the COSTS.json lockfile.  Off-TPU this is the
    # full pass (lockfile + quantitative cost contracts); on TPU the
    # quantitative contracts pin CPU XLA-twin structure and are skipped,
    # and the diff runs only if the lockfile carries a 'tpu' section —
    # otherwise the capture records the skip note instead of vacuously
    # passing.
    from cpgisland_tpu.analysis import cost_contracts as graft_costs

    creport = graft_costs.run_cost_pass()
    if not creport["ok"]:
        raise AssertionError(
            "parity-gate costs: " + graft_costs.format_failure(creport)
        )
    out["costs"] = {
        "entries_diffed": creport["diff"]["checked"],
        "cost_contracts": len(creport["contracts"]),
        "notes": creport["diff"]["notes"],
    }

    # --- graftmem layer 5 on the capturing backend: the closed-form VMEM
    # contracts (shipped-knob budget incl. stacked M=3, derived seq-shard
    # cap, stacked envelope) are pure arithmetic and run everywhere; the
    # liveness traces are skipped on TPU (they pin CPU XLA-twin structure
    # — the committed MEMORY.json carries the cpu section) and run in
    # full off-TPU.
    from cpgisland_tpu.analysis import mem_contracts as graft_mem

    mreport = graft_mem.run_mem_pass(trace=not on_tpu)
    if not mreport["ok"]:
        raise AssertionError(
            "parity-gate mem: " + graft_mem.format_failure(mreport)
        )
    out["mem"] = {
        "entries_diffed": mreport["diff"]["checked"],
        "kernels_diffed": mreport["diff"]["kernels_checked"],
        "mem_contracts": len(mreport["contracts"]),
        "notes": mreport["diff"]["notes"],
    }

    log(
        "parity-gate: OK — dense and reduced lowerings agree on this "
        f"backend ({jax.default_backend()}): " + json.dumps(out)
    )
    return out


def _stats_maxrel(st_d, st_o, what: str) -> float:
    """Max relative difference across SuffStats count tensors + loglik;
    raises past tolerance (counts: different f32 accumulation orders over
    millions of terms put agreement at ~1e-4 rel, not bit level)."""
    worst = 0.0
    for name in ("init", "trans", "emit"):
        a, b = getattr(st_d, name), getattr(st_o, name)
        denom = np.maximum(np.abs(a), 1e-2 * max(float(np.abs(a).max()), 1e-9))
        worst = max(worst, float((np.abs(a - b) / denom).max()))
    ll_rel = abs(float(st_d.loglik) - float(st_o.loglik)) / max(
        abs(float(st_d.loglik)), 1.0
    )
    worst = max(worst, ll_rel)
    if worst > 2e-3:
        raise AssertionError(f"parity-gate {what}: stats max rel diff {worst:.2e}")
    return worst


def bench_serve(engine: str = "auto", n_decode: int = 16,
                n_posterior: int = 8) -> dict:
    """Sustained serving-broker throughput + queue->result latency.

    Drives the serve subsystem the way the daemon does — a Session + an
    in-process RequestBroker with a saturated mixed queue (decode +
    posterior, two tenants) — and measures sustained flush throughput and
    per-request queue->result latency (p50/p99).  The chained-timing rules
    apply in adapted form: each FLUSH is one blocking dispatch unit (that
    round trip IS the product's serving latency, so it belongs in the
    number), every request carries distinct rng content (no two
    submissions byte-identical — phantom defense), and the throughput is
    gated by the plausibility ceiling.  A warmup pass compiles every
    geometry first; the measured pass therefore also certifies the
    flush program is dispatch-stable (the graftcheck serve contract pins
    the zero-fresh-compile property itself).
    """
    import jax

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.serve.broker import BrokerConfig, RequestBroker
    from cpgisland_tpu.serve.session import Session

    on_tpu = jax.default_backend() == "tpu"
    params = presets.durbin_cpg8()
    rec = (2 << 20) if on_tpu else (1 << 16)
    flush = (8 << 20) if on_tpu else (1 << 18)
    sess = Session(
        params, engine=engine, name="bench-serve", private_breaker=True
    )
    broker = RequestBroker(
        sess, BrokerConfig(flush_symbols=flush, flush_deadline_s=0.0)
    )
    rng = np.random.default_rng(11)

    def make_requests(base: int):
        out = []
        for i in range(n_decode + n_posterior):
            kind = "decode" if i < n_decode else "posterior"
            n = rec if kind == "decode" else max(rec // 4, 1 << 14)
            out.append(
                (base + i, kind, rng.integers(0, 4, size=n).astype(np.uint8))
            )
        return out

    from cpgisland_tpu import obs
    from cpgisland_tpu.obs.metrics import Histogram

    def run(base: int):
        reqs = make_requests(base)
        t_submit = {}
        t0 = time.perf_counter()
        for rid, kind, syms in reqs:
            broker.submit(
                request_id=rid, tenant=f"t{rid % 2}", kind=kind,
                symbols=syms, name=f"r{rid}",
            )
            t_submit[rid] = time.perf_counter()
        # Latency percentiles via the graftscope histogram machinery — the
        # SAME log-binned estimator the serve daemon's kind=stats and
        # --metrics-interval snapshots report, so bench figures and live
        # SLO figures are one estimator (quarter-octave bins: <=~9%
        # relative quantile error, exact count/min/max).
        lat = Histogram()
        while broker.pending():
            for r in broker.flush_once():
                if not r.ok:
                    raise RuntimeError(
                        f"serve bench request {r.id} failed: {r.error}"
                    )
                lat.observe(time.perf_counter() - t_submit[r.id])
        wall = time.perf_counter() - t0
        return float(sum(s.size for _, _, s in reqs)), wall, lat

    run(0)  # warmup: one compile per geometry
    warm_flushes = broker.flushes
    total, wall, lat = run(1000)
    tput = _check_plausible(total / wall, "serve")
    # No 'serve' marker exists in BASELINE.md until the first chip capture,
    # so the per-path net above degrades to the global 20 Gsym/s ceiling —
    # too wide to catch a phantom relay serving ~0 ms flushes.  Provisional
    # tighter gate: the broker's flat flush path cannot outrun pure batched
    # decode (it IS batched decode plus queueing, posterior records, and
    # island calling), so the batched-decode ceiling bounds serve too.
    serve_ceiling = _path_ceilings().get("batched-decode", float("inf"))
    if tput > serve_ceiling:
        raise RuntimeError(
            f"serve: {tput/1e6:.1f} Msym/s exceeds the provisional ceiling "
            f"({serve_ceiling/1e6:.0f} Msym/s = the batched-decode per-path "
            "ceiling; a mixed serve queue cannot outrun pure batched "
            "decode) — phantom relay result; re-run this phase in a fresh "
            "process"
        )

    snap = lat.snapshot()
    # Full histogram into the --metrics-out sidecar (stdout stays ONE JSON
    # line — this rides the obs JSONL only when an observer is active).
    obs.event("serve_slo", latency_s=lat.to_wire(), snapshot=snap)
    out = {
        "serve_msym_per_s": round(tput / 1e6, 1),
        "serve_p50_ms": round(snap["p50"] * 1e3, 2),
        "serve_p99_ms": round(snap["p99"] * 1e3, 2),
        "serve_requests": snap["count"],
        "serve_flushes": broker.flushes - warm_flushes,
    }
    log(
        f"serve: {tput/1e6:.1f} Msym/s sustained over "
        f"{out['serve_flushes']} flushes; queue->result p50 "
        f"{out['serve_p50_ms']} ms / p99 {out['serve_p99_ms']} ms "
        f"({snap['count']} requests, histogram-estimated percentiles); "
        f"fresh-input user path — upload-bound on the relayed dev setup, "
        f"compare via serve_vs_batched_decode, not this absolute"
    )
    return out


def bench_compare(engine: str = "auto") -> dict:
    """Multi-model posterior comparison throughput (family.compare).

    Runs the 3-member default cast (durbin8, two_state, null) over one
    record through the SAME record units the posterior pipeline dispatches
    plus the scoring pass, and reports MODEL-SYMBOLS/s (symbols x members
    per wall second) — the workload's native unit.  This is a fresh-input
    multi-dispatch USER path (per-member blocking units + per-rep upload),
    not a chained-timing kernel number: per the CLAUDE.md measurement
    rules its absolute is upload/RTT-bound on the relayed dev setup, so
    the published ratio is ``compare_vs_separate_runs`` — the SAME member
    set timed as N separate single-member runs through the identical
    machinery (same per-byte uploads, same dispatch shapes), isolating
    the comparison layer's cost against its own exactness contract ("N
    independent runs").  Phantom defenses kept: each rep perturbs one
    symbol, compare_record blocks internally, and the throughput is gated
    by the global plausibility ceiling plus a provisional per-path one (a
    comparison cannot outrun pure single-model posterior, so the
    posterior per-path ceiling bounds it).
    """
    import jax

    from cpgisland_tpu import family

    on_tpu = jax.default_backend() == "tpu"
    n = (2 << 20) if on_tpu else (1 << 16)
    members = family.members_from_names(("durbin8", "two_state", "null"))
    rng = np.random.default_rng(23)
    base = rng.integers(0, 4, size=n).astype(np.uint8)

    state = {}

    def run_members(ms, seed: int, tag: str, stacked: bool = True):
        rec = base.copy()
        rec[seed % n] = (rec[seed % n] + 1) % 4  # distinct request per rep
        state[tag] = family.compare_record(
            ms, rec, record=f"bench{seed}", engine=engine, stacked=stacked
        )

    def run(seed: int):
        run_members(members, seed, "rc")

    run(0)  # warmup: compiles per member geometry
    # De-stacked arm warmup doubles as the bit-identity gate (same seed-0
    # record as the stacked warmup): stacking must never change results.
    run_members(members, 0, "rc_seq", stacked=False)
    for a, b in zip(state["rc"].members, state["rc_seq"].members):
        if a.loglik != b.loglik or not np.array_equal(a.conf, b.conf):
            raise RuntimeError(
                f"compare: stacked vs sequential diverged for {a.name} — "
                "the bit-identity contract broke; do not publish"
            )
    best = _best_wall(run)
    # De-stacked wall on the SAME member set — the launch-level A/B behind
    # the `stacked` default's on-chip decision rule (BASELINE.md
    # "Multi-model occupancy"); identical machinery and uploads, so the
    # wall ratio isolates the stacked launch set.
    seq_wall = _best_wall(
        lambda s: run_members(members, s, "rc_seq", stacked=False)
    )
    # Same-path baseline: the SAME member set as N separate single-member
    # runs through the identical machinery (same uploads, same dispatch
    # shapes) — the acceptance framing "bit-identical to N independent
    # posterior runs" as a wall ratio, and a same-path denominator per the
    # CLAUDE.md rule (never ratio against a chained-timing number).
    sep_wall = 0.0
    for j, m in enumerate(members):
        run_members([m], 0, f"rc1_{j}")  # warmup
        sep_wall += _best_wall(lambda s, m=m, j=j: run_members([m], s, f"rc1_{j}"))
    model_syms = float(n * len(members))
    tput = _check_plausible(model_syms / best, "compare")
    # No 'compare' marker exists in BASELINE.md until the first chip
    # capture, so the per-path net degrades to the global ceiling — add
    # the provisional posterior bound (see docstring).
    ceil = _path_ceilings().get("posterior", float("inf"))
    if tput > ceil:
        raise RuntimeError(
            f"compare: {tput/1e6:.1f} Msym/s (model-symbols) exceeds the "
            f"provisional ceiling ({ceil/1e6:.0f} Msym/s = the posterior "
            "per-path ceiling; N-model comparison cannot outrun one-model "
            "posterior) — phantom relay result; re-run this phase in a "
            "fresh process"
        )
    # How many members actually grouped into a stacked dispatch under this
    # engine/backend (0 off-TPU under auto — the CPU resolver picks xla).
    from cpgisland_tpu.family import stacked as stacked_mod
    from cpgisland_tpu.parallel.posterior import resolve_fb_engine

    fb_engs = [
        None if m.is_null else resolve_fb_engine(engine, m.params)
        for m in members
    ]
    n_stacked = sum(
        len(v) for v in stacked_mod.stack_groups(members, fb_engs).values()
    )
    rc = state["rc"]
    out = {
        "compare_msym_per_s": round(tput / 1e6, 1),
        "compare_models": len(members),
        # Wall of the N separate single-member runs over the N-member
        # comparison's wall: ~1.0 = the comparison layer costs the same
        # as running each member independently (its exactness contract);
        # > 1.0 = the shared stream/prep/stacked launches make comparison
        # cheaper (toward N/1 fixed-cost share once the stacked dispatch
        # engages — r12).
        "compare_vs_separate_runs": round(sep_wall / best, 2),
        # The launch-level A/B on the SAME member set: de-stacked wall /
        # stacked wall (>1 = stacking wins; the on-chip decision rule for
        # the `stacked` default, same pattern as `fused`).
        "compare_stacked_vs_sequential": round(seq_wall / best, 2),
        "compare_stacked_members": n_stacked,
        "compare_winner_islands": len(rc.winner_calls),
        "compare_log_odds": {
            m.name: round(m.log_odds, 2) for m in rc.members
        },
    }
    log(
        f"compare: {tput/1e6:.1f} Msym/s model-symbols over "
        f"{len(members)} members at {n/2**20:.2f} MiB "
        f"(vs the same members as separate runs: "
        f"x{out['compare_vs_separate_runs']}); "
        f"winner track {out['compare_winner_islands']} islands; "
        "fresh-input user path — upload-bound on the relayed dev setup, "
        "compare via compare_vs_separate_runs, not the absolute"
    )
    return out


def bench_em_family(engine: str = "auto", n_members: int = 3) -> dict:
    """Stacked multi-model EM iteration (fb_pallas.batch_stats_pallas_stacked
    + per-member M-steps — train.backends.FamilyEStep's program) vs the
    SAME members as N sequential chunked EM passes.

    The family-scan training lever of ROADMAP item 2, benched per the
    CLAUDE.md rules: chained iterations inside one jit, params-side seed
    folds (the shared symbol batch stays byte-identical), every rep
    fetches a small output, and a BIT-IDENTITY gate per member before any
    timing.  Rates are model-symbols/s/iter; the plausibility gate bounds
    the per-iteration STREAM rate by the em ceiling (a stacked launch
    cannot outrun one ideal single-model E-step on stream symbols).
    """
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.ops import fb_pallas
    from cpgisland_tpu.train.baum_welch import em_update

    if engine not in ("auto", "onehot"):
        # The stacked E-step IS the reduced machinery — an explicit dense
        # engine request has no stacked arm; emitting onehot figures under
        # an xla/pallas label would misattribute the A/B.
        log(f"em-family: skipped under --engine {engine} (reduced-only)")
        return {}
    on_tpu = jax.default_backend() == "tpu"
    chunk = (1 << 16) if on_tpu else (1 << 13)
    n_chunks = 64 if on_tpu else 8
    chain = 4 if on_tpu else 2
    members = tuple(
        [presets.durbin_cpg8()]
        + [
            presets.random_hmm(jax.random.PRNGKey(i), 8, 4, partition=2)
            for i in range(1, n_members)
        ]
    )
    rng = np.random.default_rng(29)
    chunks = jnp.asarray(
        rng.integers(0, 4, size=(n_chunks, chunk)).astype(np.uint8)
    )
    lengths = jnp.full(n_chunks, chunk, jnp.int32)
    total = n_chunks * chunk

    st = fb_pallas.batch_stats_pallas_stacked(members, chunks, lengths)
    for m, p in enumerate(members):
        ref = fb_pallas.batch_stats_pallas(p, chunks, lengths, onehot=True)
        for f in ("init", "trans", "emit", "loglik"):
            if not bool(jnp.all(getattr(st[m], f) == getattr(ref, f))):
                raise RuntimeError(
                    f"em-family member {m}: stacked != sequential {f} — "
                    "the bit-identity contract broke; do not publish"
                )

    def make(stacked: bool):
        # Data arrives as ARGUMENTS, never closed over (the remote-compile
        # rule: a baked constant ships with the program bytes).
        @jax.jit
        def chained(ps, chunks, lengths, s):
            ps = tuple(
                _dc.replace(
                    p, log_pi=p.log_pi - s.astype(jnp.float32) * 1e-7
                )
                for p in ps
            )

            def body(ps, _):
                if stacked:
                    stats = fb_pallas.batch_stats_pallas_stacked(
                        ps, chunks, lengths
                    )
                else:
                    stats = tuple(
                        fb_pallas.batch_stats_pallas(
                            p, chunks, lengths, onehot=True
                        )
                        for p in ps
                    )
                return tuple(
                    em_update(p, stx)[0] for p, stx in zip(ps, stats)
                ), None

            ps, _ = jax.lax.scan(body, ps, None, length=chain)
            return ps[0].log_pi

        return chained

    out = {"em_family_members": n_members, "em_family_mi": total >> 20}
    walls = {}
    for arm in ("sequential", "stacked"):
        fn = make(arm == "stacked")
        jax.block_until_ready(fn(members, chunks, lengths, jnp.int32(0)))
        best = _best_wall(
            lambda s, fn=fn: np.asarray(
                jax.device_get(fn(members, chunks, lengths, jnp.int32(s)))
            ).sum()
        ) / chain
        _check_plausible(total / best, "em")
        walls[arm] = best
        out[f"em_family_{arm}_msym_per_s"] = round(
            total * n_members / best / 1e6, 1
        )
        log(
            f"em-family [{arm}]: "
            f"{total * n_members / best / 1e6:8.1f} Msym/s/iter "
            f"model-symbols ({best * 1e3:.2f} ms/iter)"
        )
    out["em_family_stacked_vs_sequential"] = round(
        walls["sequential"] / walls["stacked"], 2
    )
    return out


def validate_sharded_paths() -> None:
    """Run the sharded E-step configs on whatever devices exist and check the
    linear-scaling assumption structurally: count the collectives in the
    compiled HLO and assert the count is independent of sequence length.
    """
    import jax
    import jax.numpy as jnp

    from cpgisland_tpu.models import presets
    from cpgisland_tpu.parallel import fb_sharded
    from cpgisland_tpu.parallel.mesh import make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_dev = len(jax.devices())
    if n_dev < 2:
        # Single chip (the driver's TPU run): re-exec on a virtual 8-CPU mesh
        # so the sharded code paths still execute + get collective-counted —
        # the ONE shared self-provisioning helper from the dry-run entry.
        import subprocess

        from __graft_entry__ import _force_cpu_mesh_env

        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--sharded-validate-only"],
            env=_force_cpu_mesh_env(8, os.environ),
            capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        for line in proc.stderr.splitlines():
            if "sharded-validation" in line:
                log(line + " [virtual 8-CPU mesh subprocess]")
        if proc.returncode != 0:
            raise RuntimeError(f"subprocess rc={proc.returncode}: {proc.stderr[-500:]}")
        return

    params = presets.durbin_cpg8()
    mesh = make_mesh(n_dev, axis="seq")
    fn = fb_sharded.sharded_stats_fn(mesh, 256)
    rng = np.random.default_rng(4)

    def compile_and_count(total_len: int):
        obs_p, lengths = fb_sharded.shard_sequence(
            rng.integers(0, 4, size=total_len).astype(np.uint8), n_dev, 256, 4
        )
        arr = jax.device_put(jnp.asarray(obs_p), NamedSharding(mesh, P("seq")))
        lens = jax.device_put(jnp.asarray(lengths), NamedSharding(mesh, P("seq")))
        compiled = fn.lower(params, arr, lens).compile()
        hlo = compiled.as_text()
        counts = {
            op: hlo.count(f"{op}(") + hlo.count(f"{op}-start(")
            for op in ("all-reduce", "all-gather", "reduce-scatter", "collective-permute")
        }
        st = compiled(params, arr, lens)  # execute the AOT executable directly
        assert np.isfinite(float(st.loglik))
        return counts

    small = compile_and_count(n_dev * 512)
    big = compile_and_count(n_dev * 4096)
    if small != big:
        raise AssertionError(
            f"per-step collective count depends on sequence length: {small} vs {big} "
            "— the linear-scaling projection is structurally invalid"
        )
    total = sum(small.values())
    log(
        f"sharded-validation: OK — seq-parallel E-step ran on {n_dev} devices; "
        f"compiled collectives {small} (total {total}) identical at 512 and "
        "4096 symbols/device -> comms are length-independent, linear scaling "
        "projection is structurally sound"
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    # 256 MiB = the clean path's per-span decode unit (pipeline.CLEAN_DECODE_SPAN)
    # and ~one large chromosome — the size the north-star workload actually
    # decodes at; 64 MiB understates steady-state throughput by ~30%.  None =
    # resolve after the backend is known (256 on TPU, 16 on CPU where 256 MiB
    # would take minutes at ~4 Msym/s for no benefit).
    ap.add_argument("--decode-mib", type=int, default=None)
    ap.add_argument("--em-chunks", type=int, default=512)
    ap.add_argument("--engine", default="auto", choices=("auto", "xla", "pallas"))
    ap.add_argument("--platform", default="auto", help="auto|cpu|tpu (axon ignores JAX_PLATFORMS)")
    ap.add_argument(
        "--extended",
        action="store_true",
        help="also measure BASELINE.md configs (batched multi-genome decode, "
        "2-state EM, true file->islands end-to-end); extra results go to "
        "stderr, stdout stays one JSON line",
    )
    ap.add_argument(
        "--e2e-mbases",
        type=int,
        default=None,
        help="end-to-end file size in Mbases for --extended (default 64 on TPU, 4 on CPU)",
    )
    ap.add_argument(
        "--sharded-validate-only",
        action="store_true",
        help="internal: run only the sharded-path validation (used by the "
        "virtual-CPU-mesh subprocess when the parent has a single device)",
    )
    ap.add_argument(
        "--metrics-out",
        default=None,
        help="append a runtime-telemetry JSONL sidecar (cpgisland_tpu.obs "
        "spans + engine decisions + dispatch/compile ledger) to this path; "
        "stdout stays the ONE result JSON line.  The --extended parent "
        "passes it through to every phase subprocess, so one sidecar file "
        "accompanies the whole captured artifact",
    )
    ap.add_argument(
        "--phase",
        default=None,
        choices=("parity", "core", "ext1", "ext2", "ext3", "serve", "compare"),
        help="internal: run ONE capture phase and print its results as JSON "
        "(the --extended parent orchestrates phases as subprocesses — the "
        "relay tunnel degrades into phantom ~0 ms results after ~15 min of "
        "one process's use, and a fresh process resets it)",
    )
    args = ap.parse_args()

    if args.extended and args.phase is None:
        # Parent: never initializes the TPU itself (children own the tunnel
        # claim one at a time); relays every child's stderr verbatim so the
        # captured artifact stays ONE stream.
        return _orchestrate(args)

    import jax

    if args.sharded_validate_only:
        # Subprocess re-exec: pin CPU via config (site plugins override the
        # env var; see __graft_entry__._main for the same pattern).
        jax.config.update("jax_platforms", "cpu")
        validate_sharded_paths()
        return 0

    if args.platform != "auto":
        jax.config.update("jax_platforms", args.platform)
    log(f"devices: {jax.devices()}")
    on_tpu = jax.default_backend() == "tpu"
    if args.decode_mib is None:
        args.decode_mib = 256 if on_tpu else 16

    if args.metrics_out:
        # Telemetry sidecar: spans/engine decisions/ledger go to the JSONL
        # file (MetricsLogger appends, so the per-phase subprocesses of an
        # --extended run share ONE sidecar); stdout remains one JSON line.
        from cpgisland_tpu import obs as obs_mod

        with obs_mod.observe(metrics=args.metrics_out) as ob:
            ob.emit_event("bench_phase", phase=args.phase or "core")
            return _run_phase(args, on_tpu)
    return _run_phase(args, on_tpu)


def _run_phase(args, on_tpu: bool) -> int:
    if args.phase == "parity":
        out = bench_parity(4 if on_tpu else 1)
        print(json.dumps(
            {"parity": out, "armed_ceilings": armed_ceilings_record()}
        ))
        return 0

    if args.phase in (None, "core"):
        decode_tput = bench_decode(args.decode_mib * (1 << 20), engine=args.engine)
        em_tput = bench_em(args.em_chunks, engine=args.engine)
        try:
            validate_sharded_paths()
        except Exception as e:  # never let validation sink the headline number
            log(f"sharded-validation: FAILED {type(e).__name__}: {e}")
        if args.phase == "core":
            print(json.dumps({
                "decode_tput": decode_tput, "em_tput": em_tput,
                "armed_ceilings": armed_ceilings_record(),
            }))
            return 0
        _print_northstar(decode_tput, em_tput)
        return 0

    if args.phase == "ext1":
        from cpgisland_tpu.models import presets as _presets

        batched_tput = bench_batched_decode(16, 4 << 20, engine=args.engine)
        # Posterior working set is ~72 B/symbol (alpha+beta streams), so it
        # benches at half the decode size to stay well inside HBM.
        posterior_tput = bench_posterior(
            args.decode_mib * (1 << 19), engine=args.engine
        )
        em2_tput = bench_em_2state(256)
        decode2_tput = bench_decode(
            args.decode_mib * (1 << 20), engine=args.engine,
            params=_presets.two_state_cpg(), tag="-2state",
        )
        em_fused = bench_em_fused_dispatches()
        print(json.dumps({
            "batched_tput": batched_tput, "posterior_tput": posterior_tput,
            "em2_tput": em2_tput, "decode2_tput": decode2_tput,
            "em_fused": em_fused,
            "armed_ceilings": armed_ceilings_record(),
        }))
        return 0

    if args.phase == "ext2":
        # EXACT whole-sequence EM (seq / bucketed seq2d) — the flagship
        # beyond-the-reference training numbers (VERDICT r3 #3) — plus the
        # span-scale decode (VERDICT r3 #2): on TPU the production span
        # constant forces >= 2 spans (320 Mi record > CLEAN_DECODE_SPAN =
        # 256 Mi); CPU smoke-scales the same code path.
        from cpgisland_tpu.pipeline import CLEAN_DECODE_SPAN

        em_seq_tput = bench_em_seq(
            (64 << 20) if on_tpu else (2 << 20), engine=args.engine
        )
        em_seq2d_tput = bench_em_seq2d(
            engine=args.engine, scale=1.0 if on_tpu else 1 / 16
        )
        span_d = (
            bench_span_decode(320 << 20, CLEAN_DECODE_SPAN, engine=args.engine)
            if on_tpu
            else bench_span_decode(6 << 20, 4 << 20, engine=args.engine)
        )
        print(json.dumps({
            "em_seq_tput": em_seq_tput, "em_seq2d_tput": em_seq2d_tput,
            "span_d": span_d,
            "armed_ceilings": armed_ceilings_record(),
        }))
        return 0

    if args.phase == "serve":
        out = bench_serve(engine=args.engine)
        print(json.dumps(
            {"serve": out, "armed_ceilings": armed_ceilings_record()}
        ))
        return 0

    if args.phase == "compare":
        out = bench_compare(engine=args.engine)
        # The stacked-EM config rides the compare phase (same fresh
        # subprocess budget; both are the multi-model occupancy surface).
        out.update(bench_em_family(engine=args.engine))
        print(json.dumps(
            {"compare": out, "armed_ceilings": armed_ceilings_record()}
        ))
        return 0

    if args.phase == "ext3":
        from cpgisland_tpu.pipeline import POSTERIOR_SPAN

        span_p = (
            bench_span_posterior(128 << 20, POSTERIOR_SPAN, engine=args.engine)
            if on_tpu
            else bench_span_posterior(3 << 20, 1 << 21, engine=args.engine)
        )
        e2e = bench_end_to_end(
            args.e2e_mbases if args.e2e_mbases else (64 if on_tpu else 4),
            engine=args.engine,
        )
        print(json.dumps({
            "span_p": span_p, "e2e": e2e,
            "armed_ceilings": armed_ceilings_record(),
        }))
        return 0

    raise AssertionError(f"unhandled phase {args.phase!r}")


def _print_northstar(decode_tput: float, em_tput: float) -> None:
    projected = GRCH38_SYMBOLS / (decode_tput * N_CHIPS) + EM_ITERS * EM_TRAIN_SYMBOLS / (
        em_tput * N_CHIPS
    )
    log(
        f"projected v5e-8 north-star workload: {projected:.2f} s "
        f"(decode {GRCH38_SYMBOLS/(decode_tput*N_CHIPS):.2f} s + "
        f"10 EM iters {EM_ITERS*EM_TRAIN_SYMBOLS/(em_tput*N_CHIPS):.2f} s)"
    )
    print(
        json.dumps(
            {
                "metric": "grch38_decode_plus_10em_projected_v5e8_seconds",
                "value": round(projected, 3),
                "unit": "s",
                "vs_baseline": round(TARGET_SECONDS / projected, 2),
            }
        )
    )


def _tuning_census(results: dict) -> dict:
    """Fresh-vs-stale graftune winner counts for the capture platform
    (the extras' ``tuning_table_fresh`` row) — platform comes from the
    parity phase's recorded backend, so the parent process never has to
    initialize one."""
    from cpgisland_tpu.tune import table as tune_table

    platform = (
        results.get("parity", {}).get("parity", {}).get("backend", "cpu")
    )
    rep = tune_table.table_report(platform=platform)
    return {
        "platform": rep["platform"],
        "fresh": rep["fresh"],
        "stale": rep["stale"],
        "stale_keys": [r["key"] for r in rep["stale_entries"]][:8],
    }


def _pass_structure_census(results: dict) -> dict:
    """Per-path pass-structure row for the extras line: ``one_pass_armed``
    = the graftune winner the routers consult (False unless a fresh chip
    sweep flipped it — the ISSUE 17 shipped default), ``pass_structure``
    = the EXPECTED_PASSES pin of that arm (1 for the matrix-carried
    one-pass kernel, 2 for the r9 fused fwd/bwd + products)."""
    from cpgisland_tpu.analysis.cost_contracts import EXPECTED_PASSES
    from cpgisland_tpu.tune import table as tune_table

    platform = (
        results.get("parity", {}).get("parity", {}).get("backend", "cpu")
    )
    out = {}
    for path, tag in (
        ("posterior", "posterior.onehot"), ("em_seq", "em.seq.onehot")
    ):
        d = tune_table.lookup(f"one_pass.{path}", platform=platform)
        armed = bool(d.value) if (d.fresh and d.value in (True, False)) else False
        out[f"{path}_one_pass_armed"] = armed
        out[f"{path}_pass_structure"] = EXPECTED_PASSES[
            f"{tag}.onepass" if armed else tag
        ]
    return out


def _orchestrate(args) -> int:
    """--extended parent: run each capture phase in a FRESH process.

    The relay tunnel has been observed degrading into phantom ~0 ms results
    after ~15 minutes of one process's use (every run so far started healthy
    and degraded late); short per-phase subprocesses keep each session well
    under that, the per-config plausibility ceiling turns any residual
    phantom into a loud phase failure, and the parent relays all child
    stderr verbatim so the captured artifact is still one stream.
    """
    import subprocess

    base = [
        sys.executable, os.path.abspath(__file__),
        "--platform", args.platform, "--engine", args.engine,
        "--em-chunks", str(args.em_chunks),
    ]
    if args.decode_mib is not None:
        base += ["--decode-mib", str(args.decode_mib)]
    if args.e2e_mbases is not None:
        base += ["--e2e-mbases", str(args.e2e_mbases)]
    if args.metrics_out is not None:
        base += ["--metrics-out", args.metrics_out]
    carry: dict = {}
    results: dict = {}
    # parity runs FIRST: the capture certifies the reduced kernels' on-chip
    # correctness before publishing any number they produce (VERDICT r4 #1).
    for phase in ("parity", "core", "ext1", "ext2", "ext3", "serve", "compare"):
        for attempt in range(3):
            # NO subprocess timeout: killing a child mid-TPU-execution
            # wedges the relay's tunnel claim (CLAUDE.md) — a hung phase is
            # recoverable by the operator, a wedged tunnel is not.
            proc = subprocess.run(
                base + ["--phase", phase],
                capture_output=True, text=True,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            if proc.returncode == 0:
                break
            # Phantom results / transient relay failures raise inside the
            # phase; cool the tunnel down and retry the WHOLE phase fresh
            # (its stderr is discarded — only a clean pass enters the
            # captured artifact).
            err_lines = proc.stderr.strip().splitlines() or ["<no stderr>"]
            log(
                f"phase {phase} attempt {attempt + 1} failed "
                f"(rc={proc.returncode}): ...{err_lines[-1][:200]}"
            )
            if attempt < 2:
                log("cooling down 90 s, then retrying in a fresh process")
                time.sleep(90)
        else:
            raise RuntimeError(
                f"phase {phase} failed 3 attempts: {proc.stderr[-500:]}"
            )
        sys.stderr.write(proc.stderr)
        sys.stderr.flush()
        results[phase] = json.loads(proc.stdout.strip().splitlines()[-1])
        carry.update(
            {k: v for k, v in results[phase].items() if not isinstance(v, dict)}
        )

    CHR21, CHR1 = 46.7e6, 248e6
    # Per-path plausibility ceilings as each capture phase ACTUALLY armed
    # them: a BASELINE.md marker-parse failure in any child shows up here
    # as "degraded-to-global" instead of silently widening the phantom net.
    armed = {ph: r.get("armed_ceilings") for ph, r in results.items()}
    degraded_phases = sorted(
        ph for ph, v in armed.items() if not isinstance(v, dict)
    )
    decode_tput, em_tput = carry["decode_tput"], carry["em_tput"]
    span_d, span_p = results["ext2"]["span_d"], results["ext3"]["span_p"]
    e2e = results["ext3"]["e2e"]
    extras = {
        "em_seq_msym_per_sec_chip": round(carry["em_seq_tput"] / 1e6, 1),
        "em_seq2d_msym_per_sec_chip": round(carry["em_seq2d_tput"] / 1e6, 1),
        "em_seq_chr1_iters_per_sec_v5e8": round(
            carry["em_seq_tput"] * N_CHIPS / EM_TRAIN_SYMBOLS, 2
        ),
        "span_decode_msym_per_sec_chip": span_d["span_decode_msym_per_s"],
        "span_decode_overhead_vs_one_pass": span_d["span_decode_overhead"],
        "span_posterior_msym_per_sec_chip": span_p["span_posterior_msym_per_s"],
        "span_posterior_overhead_vs_one_pass": span_p[
            "span_posterior_overhead"
        ],
        **{f"span_{k}": v for k, v in span_d.items() if k.startswith("peak_")},
        "chr21_2state_decode_projected_s": round(
            CHR21 / carry["decode2_tput"], 3
        ),
        "chr1_8state_decode_plus_islands_projected_v5e8_s": round(
            CHR1 / (decode_tput * N_CHIPS), 3
        ),
        "em_2state_chr1_iters_per_sec_v5e8": round(
            carry["em2_tput"] * N_CHIPS / EM_TRAIN_SYMBOLS, 2
        ),
        "em_8state_chr1_iters_per_sec_v5e8": round(
            em_tput * N_CHIPS / EM_TRAIN_SYMBOLS, 2
        ),
        "grch38_decode_projected_v5e8_s": round(
            GRCH38_SYMBOLS / (decode_tput * N_CHIPS), 3
        ),
        "batched_decode_genomes_per_sec_v5e8": round(
            carry["batched_tput"] * N_CHIPS / GRCH38_SYMBOLS, 3
        ),
        "batched_decode_msym_per_sec_chip": round(carry["batched_tput"] / 1e6, 1),
        "posterior_msym_per_sec_chip": round(carry["posterior_tput"] / 1e6, 1),
        "grch38_posterior_projected_v5e8_s": round(
            GRCH38_SYMBOLS / (carry["posterior_tput"] * N_CHIPS), 3
        ),
        "posterior_vs_decode": round(carry["posterior_tput"] / decode_tput, 2),
        "host_encode_vs_8chip_decode": round(
            e2e.get("encode_msym_per_s", 0.0) * 1e6 / (decode_tput * N_CHIPS), 2
        ),
        # The dispatch-amortized EM contract (obs-ledger-counted): K fused
        # steady-state iterations vs the host loop's 2K blocking syncs.
        "em_fused_blocking_dispatches_10iter": results["ext1"]["em_fused"][
            "fused_dispatches"
        ],
        "em_host_blocking_dispatches_10iter": results["ext1"]["em_fused"][
            "host_dispatches"
        ],
        "parity_gate": results["parity"]["parity"],
        # graftcheck layer-2 summary, surfaced flat so a reader of the
        # extras line sees the contract count without digging into the gate.
        "contracts_checked_on_capture_backend": results["parity"]["parity"][
            "contracts"
        ]["checked"],
        "costs_checked_on_capture_backend": results["parity"]["parity"][
            "costs"
        ],
        "mem_checked_on_capture_backend": results["parity"]["parity"][
            "mem"
        ],
        # Sustained serving-broker throughput + queue->result latency on the
        # capturing backend (the serve phase's in-process daemon run).
        **results["serve"]["serve"],
        # Serve is a fresh-input user path (every request uploads new
        # symbols), so its absolute wall is upload-bound on this relayed
        # dev setup and swings with relay bandwidth.  Publish the ratio
        # against pure batched decode from THIS artifact — same per-byte
        # upload on both sides, so the ratio isolates broker overhead
        # (CLAUDE.md rule: ratios against a same-path baseline, never
        # absolute upload-bound figures).
        "serve_vs_batched_decode": round(
            results["serve"]["serve"]["serve_msym_per_s"] * 1e6
            / carry["batched_tput"], 2
        ),
        # Multi-model comparison (family.compare): MODEL-symbols/s over the
        # 3-member default cast; the meaningful figure is the in-phase
        # compare_vs_separate_runs ratio (same-path baseline — both sides
        # pay the same per-rep upload and dispatch shape; the absolute is
        # upload/RTT-bound on the relayed dev setup).
        **results["compare"]["compare"],
        "armed_path_ceilings": (
            next((v for v in armed.values() if isinstance(v, dict)), None)
            or "degraded-to-global"
        ),
        "ceilings_degraded_phases": degraded_phases,
        # graftune winner-table census on the capturing backend: how many
        # swept knob winners the routers actually honored during this
        # capture vs how many had gone stale (COSTS.json fingerprint
        # drift = a kernel reshape since the last sweep — the
        # self-invalidation working as designed; re-sweep with
        # tools/graftune.py --all before trusting stale-knob figures).
        "tuning_table_fresh": _tuning_census(results),
        # ISSUE 17 observability: which FB arm the posterior/em-seq phases
        # were ARMED with on the capture platform (host-side graftune
        # consult, same fallback rule as the routers) and the pinned
        # T-scaling pass count of that arm — the artifact records which
        # pass structure produced the numbers.
        **_pass_structure_census(results),
    }
    log("extended: " + json.dumps(extras))
    _print_northstar(decode_tput, em_tput)
    return 0


if __name__ == "__main__":
    sys.exit(main())
