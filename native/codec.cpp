// Native DNA codec: the data-loader hot path, C++ twin of utils/codec.py.
//
// The reference's IO layer is a JVM char-by-char stream (CpGIslandFinder.java
// :112-128,:238-254 — BufferedReader.read() per character).  Here the host-side
// encode runs as a single fused pass over raw bytes: FASTA-header stripping
// (optional) + 256-entry LUT symbol mapping + compaction, with streaming state
// carried across arbitrary buffer boundaries so multi-GiB genomes encode in
// bounded memory.  Exposed through ctypes (no pybind11 in this image); the
// Python LUT path remains as fallback and as the parity oracle in tests.
//
// Build: `make -C native` (g++ -O3 -shared); loaded by cpgisland_tpu.utils.native.

#include <cstddef>
#include <cstring>
#include <cstdint>

namespace {

// LUT: A/a->0 C/c->1 G/g->2 T/t->3, everything else -> 0xFF (skip).
// Matches utils/codec.py::_LUT and the reference's char mapping.
struct Lut {
    uint8_t t[256];
    constexpr Lut() : t() {
        for (int i = 0; i < 256; ++i) t[i] = 0xFF;
        t['A'] = t['a'] = 0;
        t['C'] = t['c'] = 1;
        t['G'] = t['g'] = 2;
        t['T'] = t['t'] = 3;
    }
};
constexpr Lut kLut;

}  // namespace

extern "C" {

// Encode n raw bytes into out (caller-sized >= n); returns symbols written.
// Reference semantics: every non-ACGTacgt byte silently skipped.
size_t cpg_encode(const uint8_t* in, size_t n, uint8_t* out) {
    size_t w = 0;
    for (size_t i = 0; i < n; ++i) {
        uint8_t v = kLut.t[in[i]];
        out[w] = v;
        w += (v != 0xFF);  // branchless compaction
    }
    return w;
}

// Streaming-state bits for the FASTA-aware path (mirrors
// codec._strip_headers_stateful's (in_header, at_line_start) carry).
enum : uint32_t {
    kInHeader = 1u << 0,
    kAtLineStart = 1u << 1,
};

// Fused header-strip + encode.  *state carries (in_header, at_line_start)
// across buffer boundaries; initialize to kAtLineStart (2) for a fresh file.
// A header opens only at a '>' that begins a line and runs to end-of-line.
//
// Line-span structure: memchr jumps between newlines so the inner encode loop
// is the same tight LUT/compaction loop as cpg_encode, with the header/'>'
// checks hoisted out to once per line ('>' mid-line is not a base, so the LUT
// skips it either way — only the line-start check changes behavior).
size_t cpg_encode_fasta(const uint8_t* in, size_t n, uint8_t* out, uint32_t* state) {
    bool in_header = *state & kInHeader;
    bool at_line_start = *state & kAtLineStart;
    size_t w = 0;
    size_t i = 0;
    while (i < n) {
        if (in_header) {
            const void* nl = memchr(in + i, '\n', n - i);
            if (!nl) {
                i = n;
                at_line_start = false;
                break;
            }
            i = static_cast<size_t>(static_cast<const uint8_t*>(nl) - in) + 1;
            in_header = false;
            at_line_start = true;
            continue;
        }
        if (at_line_start && in[i] == '>') {
            in_header = true;
            continue;
        }
        const void* nl = memchr(in + i, '\n', n - i);
        size_t end = nl ? static_cast<size_t>(static_cast<const uint8_t*>(nl) - in) : n;
        for (size_t j = i; j < end; ++j) {
            uint8_t v = kLut.t[in[j]];
            out[w] = v;
            w += (v != 0xFF);
        }
        if (nl) {
            i = end + 1;
            at_line_start = true;
        } else {
            i = n;
            at_line_start = false;
        }
    }
    *state = (in_header ? kInHeader : 0u) | (at_line_start ? kAtLineStart : 0u);
    return w;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Parallel whole-buffer encode.
//
// The streaming kernels above are single-threaded (bounded memory, arbitrary
// block boundaries).  For whole-file encodes the host is the bottleneck at
// GRCh38 scale (~3 GiB), so this path fans out across threads in two phases:
// each thread counts its segment's symbols (phase 1), a tiny serial prefix
// sum fixes every segment's exact output offset, then each thread re-scans
// and writes (phase 2).  Output is dense with no compaction pass, and the
// caller allocates exactly sum(counts) bytes between the phases
// (cpg_count_segments / cpg_encode_segments).
//
// FASTA mode requires segment-local header state, so segments are aligned to
// line starts (headers never span lines); byte-aligned otherwise.

#include <algorithm>
#include <thread>
#include <vector>

namespace {

// One segment's fused strip+encode, counting always, writing when out != nullptr.
// Segment must begin at a line start in FASTA mode.
template <bool Fasta>
size_t segment_pass(const uint8_t* in, size_t begin, size_t end, uint8_t* out) {
    size_t w = 0;
    size_t i = begin;
    bool in_header = false;
    while (i < end) {
        if (Fasta) {
            if (in_header) {
                const void* nl = memchr(in + i, '\n', end - i);
                if (!nl) break;
                i = static_cast<size_t>(static_cast<const uint8_t*>(nl) - in) + 1;
                in_header = false;
                continue;
            }
            if (in[i] == '>') {  // loop invariant: i is at a line start here
                in_header = true;
                continue;
            }
        }
        const void* nl = memchr(in + i, '\n', end - i);
        size_t stop = nl ? static_cast<size_t>(static_cast<const uint8_t*>(nl) - in) : end;
        for (size_t j = i; j < stop; ++j) {
            uint8_t v = kLut.t[in[j]];
            // NOT the streaming kernels' speculative store: segments here are
            // exactly sized, so a sentinel written at out[w] would land in the
            // next thread's region (or past the buffer on the last segment).
            if (v != 0xFF) {
                if (out) out[w] = v;
                ++w;
            }
        }
        i = nl ? stop + 1 : end;
    }
    return w;
}

// Non-FASTA mode has no line structure to respect: one tight loop.
size_t segment_pass_raw(const uint8_t* in, size_t begin, size_t end, uint8_t* out) {
    size_t w = 0;
    for (size_t i = begin; i < end; ++i) {
        uint8_t v = kLut.t[in[i]];
        if (v != 0xFF) {  // no speculative store: exact-sized segment regions
            if (out) out[w] = v;
            ++w;
        }
    }
    return w;
}

std::vector<size_t> segment_bounds(const uint8_t* in, size_t n, int fasta, int nthreads) {
    size_t k = static_cast<size_t>(nthreads);
    std::vector<size_t> b;
    b.push_back(0);
    for (size_t t = 1; t < k; ++t) {
        size_t pos = n * t / k;
        if (pos <= b.back()) continue;
        if (fasta) {
            // Align to the next line start so header state is segment-local.
            const void* nl = memchr(in + pos, '\n', n - pos);
            if (!nl) break;
            pos = static_cast<size_t>(static_cast<const uint8_t*>(nl) - in) + 1;
            if (pos <= b.back() || pos >= n) continue;
        }
        b.push_back(pos);
    }
    b.push_back(n);
    return b;
}

int resolve_threads(int nthreads, size_t n) {
    if (nthreads <= 0) {
        unsigned hw = std::thread::hardware_concurrency();
        nthreads = hw ? static_cast<int>(hw) : 4;
    }
    // Below ~4 MiB per thread the spawn/join overhead beats the win.
    size_t cap = std::max<size_t>(1, n / (4u << 20));
    return static_cast<int>(std::min<size_t>(static_cast<size_t>(nthreads), cap));
}

}  // namespace

extern "C" {

// Phase 1: compute segment bounds and per-segment symbol counts.  bounds_out
// needs max_seg + 1 entries, counts_out max_seg; returns the segment count
// (0 when n == 0 or max_seg is too small for even one segment).
size_t cpg_count_segments(const uint8_t* in, size_t n, int fasta, int nthreads,
                          size_t* bounds_out, size_t* counts_out, size_t max_seg) {
    if (n == 0 || max_seg == 0) return 0;
    nthreads = resolve_threads(nthreads, n);
    if (static_cast<size_t>(nthreads) > max_seg) nthreads = static_cast<int>(max_seg);
    std::vector<size_t> bounds = segment_bounds(in, n, fasta, nthreads);
    size_t nseg = bounds.size() - 1;
    if (nseg > max_seg) return 0;
    std::vector<size_t> counts(nseg, 0);
    std::vector<std::thread> ts;
    auto count_one = [&](size_t s) {
        counts[s] = fasta ? segment_pass<true>(in, bounds[s], bounds[s + 1], nullptr)
                          : segment_pass_raw(in, bounds[s], bounds[s + 1], nullptr);
    };
    for (size_t s = 1; s < nseg; ++s) ts.emplace_back(count_one, s);
    count_one(0);
    for (auto& t : ts) t.join();
    for (size_t s = 0; s <= nseg; ++s) bounds_out[s] = bounds[s];
    for (size_t s = 0; s < nseg; ++s) counts_out[s] = counts[s];
    return nseg;
}

// Phase 2: write using phase 1's bounds/counts; out needs capacity for
// exactly sum(counts).  Returns symbols written.
size_t cpg_encode_segments(const uint8_t* in, const size_t* bounds, const size_t* counts,
                           size_t nseg, int fasta, uint8_t* out) {
    if (nseg == 0) return 0;
    std::vector<size_t> offsets(nseg, 0);
    for (size_t s = 1; s < nseg; ++s) offsets[s] = offsets[s - 1] + counts[s - 1];
    std::vector<std::thread> ts;
    auto write_one = [&](size_t s) {
        if (fasta) {
            segment_pass<true>(in, bounds[s], bounds[s + 1], out + offsets[s]);
        } else {
            segment_pass_raw(in, bounds[s], bounds[s + 1], out + offsets[s]);
        }
    };
    for (size_t s = 1; s < nseg; ++s) ts.emplace_back(write_one, s);
    write_one(0);
    for (auto& t : ts) t.join();
    return offsets[nseg - 1] + counts[nseg - 1];
}

// ABI version guard so a stale .so is rejected by the loader.
uint32_t cpg_native_abi(void) { return 3; }

}  // extern "C"
