// Native DNA codec: the data-loader hot path, C++ twin of utils/codec.py.
//
// The reference's IO layer is a JVM char-by-char stream (CpGIslandFinder.java
// :112-128,:238-254 — BufferedReader.read() per character).  Here the host-side
// encode runs as a single fused pass over raw bytes: FASTA-header stripping
// (optional) + 256-entry LUT symbol mapping + compaction, with streaming state
// carried across arbitrary buffer boundaries so multi-GiB genomes encode in
// bounded memory.  Exposed through ctypes (no pybind11 in this image); the
// Python LUT path remains as fallback and as the parity oracle in tests.
//
// Build: `make -C native` (g++ -O3 -shared); loaded by cpgisland_tpu.utils.native.

#include <cstddef>
#include <cstring>
#include <cstdint>

namespace {

// LUT: A/a->0 C/c->1 G/g->2 T/t->3, everything else -> 0xFF (skip).
// Matches utils/codec.py::_LUT and the reference's char mapping.
struct Lut {
    uint8_t t[256];
    constexpr Lut() : t() {
        for (int i = 0; i < 256; ++i) t[i] = 0xFF;
        t['A'] = t['a'] = 0;
        t['C'] = t['c'] = 1;
        t['G'] = t['g'] = 2;
        t['T'] = t['t'] = 3;
    }
};
constexpr Lut kLut;

}  // namespace

extern "C" {

// Encode n raw bytes into out (caller-sized >= n); returns symbols written.
// Reference semantics: every non-ACGTacgt byte silently skipped.
size_t cpg_encode(const uint8_t* in, size_t n, uint8_t* out) {
    size_t w = 0;
    for (size_t i = 0; i < n; ++i) {
        uint8_t v = kLut.t[in[i]];
        out[w] = v;
        w += (v != 0xFF);  // branchless compaction
    }
    return w;
}

// Streaming-state bits for the FASTA-aware path (mirrors
// codec._strip_headers_stateful's (in_header, at_line_start) carry).
enum : uint32_t {
    kInHeader = 1u << 0,
    kAtLineStart = 1u << 1,
};

// Fused header-strip + encode.  *state carries (in_header, at_line_start)
// across buffer boundaries; initialize to kAtLineStart (2) for a fresh file.
// A header opens only at a '>' that begins a line and runs to end-of-line.
//
// Line-span structure: memchr jumps between newlines so the inner encode loop
// is the same tight LUT/compaction loop as cpg_encode, with the header/'>'
// checks hoisted out to once per line ('>' mid-line is not a base, so the LUT
// skips it either way — only the line-start check changes behavior).
size_t cpg_encode_fasta(const uint8_t* in, size_t n, uint8_t* out, uint32_t* state) {
    bool in_header = *state & kInHeader;
    bool at_line_start = *state & kAtLineStart;
    size_t w = 0;
    size_t i = 0;
    while (i < n) {
        if (in_header) {
            const void* nl = memchr(in + i, '\n', n - i);
            if (!nl) {
                i = n;
                at_line_start = false;
                break;
            }
            i = static_cast<size_t>(static_cast<const uint8_t*>(nl) - in) + 1;
            in_header = false;
            at_line_start = true;
            continue;
        }
        if (at_line_start && in[i] == '>') {
            in_header = true;
            continue;
        }
        const void* nl = memchr(in + i, '\n', n - i);
        size_t end = nl ? static_cast<size_t>(static_cast<const uint8_t*>(nl) - in) : n;
        for (size_t j = i; j < end; ++j) {
            uint8_t v = kLut.t[in[j]];
            out[w] = v;
            w += (v != 0xFF);
        }
        if (nl) {
            i = end + 1;
            at_line_start = true;
        } else {
            i = n;
            at_line_start = false;
        }
    }
    *state = (in_header ? kInHeader : 0u) | (at_line_start ? kAtLineStart : 0u);
    return w;
}

// ABI version guard so a stale .so is rejected by the loader.
uint32_t cpg_native_abi(void) { return 1; }

}  // extern "C"
